"""Scheduler decision provenance: structured "why" records for every
admission verb.

The control plane makes its most consequential calls — which nodes were
rejected and why, which chip or gang slice won and by what margin — and,
until this layer, threw the evidence away the moment the webhook
response left the building. Debugging a placement then meant
reconstructing state from PATCH diffs. This module keeps the evidence:

- :class:`ScoreVector` — one placement candidate's structured score
  breakdown: the raw fractional score (full resolution — the 0-10
  integer wire projection ties most of a large fleet), the free-units /
  binpack terms behind it, and, for gang slices, the topology objective
  components (ICI hops, stranded slivers, broken whole chips,
  tie-break). This is the policy-introspection seam ROADMAP item 2's
  pluggable placement policies implement: a policy you can swap is
  useless if you cannot see what it scored.
- :class:`DecisionRecord` — one verb's full decision: pod, verb,
  candidate set size, per-node rejection reasons, per-node score
  breakdowns, the chosen placement, the admission trace id (PR 8
  stitching), and the WAL seq / ledger stamp that made it durable.
- :class:`DecisionLog` — a hard-bounded in-memory ring of records plus
  an optional fsync-free on-disk segment log (JSON lines, size-rotated),
  served as JSON on the metrics endpoint's ``/decisions`` path and
  rendered by ``kubectl-inspect-tpushare why``.

Emission is designed for the hot path: records are built from values the
verbs already computed (the reason dicts and score maps are stored by
reference, never deep-copied — emitters hand over freshly-built dicts
they do not mutate afterwards), appending to the ring is one deque op
under a near-leaf lock, and a disabled log returns before touching the
lock. The segment write runs under its own I/O-ranked lock and never
fsyncs — provenance is an observability artifact, not a durability one
(the WAL owns durability; the record carries its seq as the join key).

``tools/tpulint``'s ``decision-outcome`` rule pins the emission
discipline statically: a function that emits decision records must emit
on every outcome path (success, rejection, early return), reusing the
``rules_wal`` CFG-outcome machinery.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from typing import Any, Iterable

from .lockrank import make_lock

# Ring default: enough for the storm bench's largest round plus slack;
# one record is a few hundred bytes of references.
DEFAULT_MAX_RECORDS = 512
# Segment rotation bound (bytes): the on-disk log is a ring too. Two
# files at most live on disk: the active segment and one rotated-out
# predecessor, so a postmortem always has at least SEGMENT_MAX_BYTES of
# history even right after a rotation.
DEFAULT_SEGMENT_MAX_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class ScoreVector:
    """One candidate's structured placement score.

    ``raw`` is the full-resolution fractional score on the 0-10 scale
    (ties broken deterministically by it — see :func:`rank_scores`);
    ``projected`` is the 0-10 integer the webhook wire format pins.
    Single-chip placements carry the binpack terms only; gang slices add
    the lexicographic topology objective (ICI hops, stranded slivers,
    broken whole chips, lowest-chip tie-break) from
    ``topology.best_slice_scored``.
    """

    policy: str
    raw: float
    free_units: int
    request_units: int
    binpack: float  # slack fraction on the decisive chip: (free-req)/cap
    ici_hops: int | None = None
    stranded: int | None = None
    broken: int | None = None
    tie_break: int | None = None

    @property
    def projected(self) -> int:
        """The 0-10 integer webhook score (round + clamp of ``raw``)."""
        return max(0, min(10, round(self.raw)))

    def to_dict(self) -> dict[str, Any]:
        doc: dict[str, Any] = {
            "policy": self.policy,
            "raw": round(self.raw, 4),
            "projected": self.projected,
            "free_units": self.free_units,
            "request_units": self.request_units,
            "binpack": round(self.binpack, 4),
        }
        if self.ici_hops is not None:
            doc["ici_hops"] = self.ici_hops
        if self.stranded is not None:
            doc["stranded"] = self.stranded
        if self.broken is not None:
            doc["broken"] = self.broken
        if self.tie_break is not None:
            doc["tie_break"] = self.tie_break
        return doc


ZERO_SCORE = ScoreVector(
    policy="", raw=0.0, free_units=0, request_units=0, binpack=0.0
)


def rank_scores(scores: dict[str, "ScoreVector"]) -> list[str]:
    """Node names best-first: raw score descending (full resolution —
    the deterministic tie-break the 0-10 projection cannot provide),
    then name ascending so equal-raw fleets still order stably."""
    return sorted(scores, key=lambda n: (-scores[n].raw, n))


def chip_breakdown(
    free_units: int,
    cap: int,
    idx: int | None,
    request_units: int,
    policy: str,
) -> ScoreVector:
    """Breakdown for one decisive chip — THE policy scoring formula, in
    one place: the extender's node scores (``logic._score_free``
    delegates here), its bind records, and the allocator's placement
    records all describe a decision in the same terms, so ``inspect
    why`` can never show a margin the scheduler did not compute. ``idx``
    is the chip-index tie-break for concrete chip decisions (None when
    scoring a node's best case rather than a chosen chip)."""
    if cap <= 0 or free_units < request_units:
        return ScoreVector(
            policy=policy, raw=0.0, free_units=max(0, free_units),
            request_units=request_units, binpack=0.0, tie_break=idx,
        )
    binpack = (free_units - request_units) / cap
    raw = 10.0 * binpack if policy == "spread" else 10.0 * (1.0 - binpack)
    return ScoreVector(
        policy=policy, raw=raw, free_units=free_units,
        request_units=request_units, binpack=binpack, tie_break=idx,
    )


@dataclasses.dataclass(frozen=True)
class DecisionRecord:
    """One admission verb's decision, as emitted (immutable thereafter).

    ``scores`` maps candidate -> :class:`ScoreVector` (or an
    already-serialized dict of one); ``rejected`` maps candidate ->
    human-readable reason — both stored by reference from the emitting
    verb. ``placement`` is the chosen concrete placement (chip / member
    chips / shape / units), ``seq`` the WAL sequence that journaled it
    (None when unjournaled), ``trace_id`` the PR 8 admission trace.
    """

    pod: str
    verb: str
    outcome: str  # "ok" | "error"
    id: int = 0  # per-process monotonic, stamped by the log
    time_unix: float = 0.0
    node: str = ""
    reason: str = ""  # outcome="error": why the verb failed
    candidates: int = 0
    rejected: dict[str, str] = dataclasses.field(default_factory=dict)
    scores: dict[str, Any] = dataclasses.field(default_factory=dict)
    placement: dict[str, Any] = dataclasses.field(default_factory=dict)
    moves: tuple[str, ...] = ()  # defrag plans: affected pod keys
    trace_id: str = ""
    seq: int | None = None
    # Sharded extender provenance: which shard made this decision, and —
    # for router-merged batch verbs — which shards were NEVER consulted
    # (unreachable / partitioned), so "rejected" and "not consulted" are
    # distinguishable in `inspect why`. A node owned by a degraded shard
    # was not scored at all; its absence from `rejected` is not a pass.
    shard: str = ""
    degraded_shards: tuple[str, ...] = ()

    def to_dict(self) -> dict[str, Any]:
        doc: dict[str, Any] = {
            "id": self.id,
            "time_unix": self.time_unix,
            "pod": self.pod,
            "verb": self.verb,
            "outcome": self.outcome,
        }
        if self.node:
            doc["node"] = self.node
        if self.reason:
            doc["reason"] = self.reason
        if self.candidates:
            doc["candidates"] = self.candidates
        if self.rejected:
            doc["rejected"] = dict(self.rejected)
        if self.scores:
            doc["scores"] = {
                name: (sv.to_dict() if isinstance(sv, ScoreVector) else sv)
                for name, sv in self.scores.items()
            }
        if self.placement:
            doc["placement"] = dict(self.placement)
        if self.moves:
            doc["moves"] = list(self.moves)
        if self.trace_id:
            doc["trace_id"] = self.trace_id
        if self.seq is not None:
            doc["seq"] = self.seq
        if self.shard:
            doc["shard"] = self.shard
        if self.degraded_shards:
            doc["degraded_shards"] = list(self.degraded_shards)
        return doc


class DecisionLog:
    """Bounded ring of :class:`DecisionRecord` + optional segment log.

    The ring is a ``deque(maxlen=...)`` — hard-bounded by construction,
    a storm can only evict, never grow it. The segment log appends one
    JSON line per record with NO fsync and rotates by size (active file
    + one predecessor). Both sides live behind separate locks so the
    pure-memory append never waits on the disk."""

    def __init__(
        self,
        max_records: int = DEFAULT_MAX_RECORDS,
        segment_path: str = "",
        segment_max_bytes: int = DEFAULT_SEGMENT_MAX_BYTES,
    ) -> None:
        self._lock = make_lock("decisions.ring")
        self._io_lock = make_lock("decisions.segment")
        self._ring: deque[DecisionRecord] = deque(maxlen=max_records)
        self._enabled = True
        self._seq = 0
        self._dropped = 0
        self._segment_path = segment_path
        self._segment_max = segment_max_bytes
        self._segment_file: Any = None
        self._segment_bytes = 0

    # --- configuration ----------------------------------------------------

    def configure(
        self,
        enabled: bool | None = None,
        max_records: int | None = None,
        segment_path: str | None = None,
        segment_max_bytes: int | None = None,
    ) -> None:
        """Runtime reconfiguration (daemon/extender flags, the bench's
        decisions-off A/B half). Shrinking ``max_records`` keeps the
        newest records; ``segment_path=""`` closes the segment log."""
        close_file = None
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if max_records is not None and max_records != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, max_records))
        with self._io_lock:
            if segment_max_bytes is not None:
                self._segment_max = segment_max_bytes
            if segment_path is not None and segment_path != self._segment_path:
                close_file = self._segment_file
                self._segment_file = None
                self._segment_bytes = 0
                self._segment_path = segment_path
        if close_file is not None:
            try:
                close_file.close()
            except OSError:
                pass

    @property
    def enabled(self) -> bool:
        return self._enabled

    # --- emission ---------------------------------------------------------

    def emit(
        self,
        pod: str,
        verb: str,
        outcome: str = "ok",
        *,
        node: str = "",
        reason: str = "",
        candidates: int = 0,
        rejected: dict[str, str] | None = None,
        scores: dict[str, Any] | None = None,
        placement: dict[str, Any] | None = None,
        moves: Iterable[str] = (),
        trace_id: str = "",
        seq: int | None = None,
        shard: str = "",
        degraded_shards: Iterable[str] = (),
    ) -> DecisionRecord | None:
        """Record one decision; returns the stamped record (None when the
        log is disabled). The dict arguments are stored by reference —
        callers hand over dicts they built for this record and do not
        mutate afterwards (the verbs' reason/score maps are built fresh
        per request, so this is free)."""
        if not self._enabled:
            return None
        now = time.time()
        with self._lock:
            self._seq += 1
            record = DecisionRecord(
                pod=pod,
                verb=verb,
                outcome=outcome,
                id=self._seq,
                time_unix=now,
                node=node,
                reason=reason,
                candidates=candidates,
                rejected=rejected if rejected is not None else {},
                scores=scores if scores is not None else {},
                placement=placement if placement is not None else {},
                moves=tuple(moves),
                trace_id=trace_id,
                seq=seq,
                shard=shard,
                degraded_shards=tuple(degraded_shards),
            )
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(record)
            segment_on = bool(self._segment_path)
        if segment_on:
            self._segment_write(record)
        return record

    # --- segment log ------------------------------------------------------

    def _segment_write(self, record: DecisionRecord) -> None:
        """One JSON line, no fsync; size-rotate keeping one predecessor.
        Best-effort by design — a sick disk must not hurt admission."""
        line = json.dumps(record.to_dict(), separators=(",", ":")) + "\n"
        data = line.encode()
        with self._io_lock:
            try:
                if self._segment_file is None:
                    self._open_segment()
                if self._segment_bytes + len(data) > self._segment_max:
                    self._rotate_segment()
                self._segment_file.write(data)
                self._segment_file.flush()  # OS buffer, NOT fsync
                self._segment_bytes += len(data)
            except OSError:
                # drop the line; the in-memory ring still has the record.
                # Close (best-effort) before dropping the reference — a
                # sick disk must not also churn leaked descriptors.
                if self._segment_file is not None:
                    try:
                        self._segment_file.close()
                    except OSError:
                        pass
                self._segment_file = None

    def _open_segment(self) -> None:
        directory = os.path.dirname(self._segment_path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._segment_file = open(self._segment_path, "ab")  # noqa: SIM115
        self._segment_bytes = self._segment_file.tell()

    def _rotate_segment(self) -> None:
        self._segment_file.close()
        os.replace(self._segment_path, self._segment_path + ".1")
        self._segment_file = open(self._segment_path, "ab")  # noqa: SIM115
        self._segment_bytes = 0

    def close(self) -> None:
        with self._io_lock:
            if self._segment_file is not None:
                try:
                    self._segment_file.close()
                except OSError:
                    pass
                self._segment_file = None

    # --- readers ----------------------------------------------------------

    def records(
        self,
        pod: str | None = None,
        verb: str | None = None,
        limit: int | None = None,
    ) -> list[DecisionRecord]:
        """Matching records, oldest first. ``pod`` matches the record's
        pod key or (for defrag plans) any pod its moves touch."""
        with self._lock:
            snapshot = list(self._ring)
        out = [
            r for r in snapshot
            if (pod is None or r.pod == pod or pod in r.moves)
            and (verb is None or r.verb == verb)
        ]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def size(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    def to_doc(
        self,
        pod: str | None = None,
        verb: str | None = None,
        limit: int | None = None,
    ) -> dict[str, Any]:
        """The ``/decisions`` endpoint body."""
        records = self.records(pod=pod, verb=verb, limit=limit)
        with self._lock:
            dropped, max_records = self._dropped, self._ring.maxlen
        return {
            "max_records": max_records,
            "dropped": dropped,
            "records": [r.to_dict() for r in records],
        }


# Process-wide default log, mirroring metrics.REGISTRY / tracing.STORE:
# one decision log per control-plane process.
DECISIONS = DecisionLog()
