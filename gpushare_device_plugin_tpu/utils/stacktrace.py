"""All-thread stack dump (reference: ``coredump.go:10-30`` + SIGQUIT wiring).

The reference grows a buffer around ``runtime.Stack(all=true)`` and writes
``/etc/kubernetes/go_<ts>.txt``; Python gives us the same via
``sys._current_frames``.
"""

from __future__ import annotations

import sys
import time
import traceback
from threading import enumerate as all_threads


def stack_trace() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in all_threads()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out)


def coredump(dir_path: str = "/etc/kubernetes") -> str:
    path = f"{dir_path}/tpushare_{int(time.time())}.txt"
    with open(path, "w") as f:
        f.write(stack_trace())
    return path
