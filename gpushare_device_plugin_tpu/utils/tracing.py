"""Zero-dependency distributed tracing for the admission and serving paths.

The reference plugin's observability story is glog lines plus the inspect
CLI reading apiserver state (SURVEY.md section 5); metrics (``.metrics``)
added the aggregate half. This module adds the *per-decision* half: why
did THIS pod's admission take 40 ms / fail / land on that chip, and why
did THIS request's TTFT blow its SLO. It is deliberately OpenTelemetry-
shaped (spans with ids/parents/attributes/events, OTLP-JSON export)
without the dependency — the image installs nothing.

Pieces:

- :class:`Span` / :class:`SpanContext` — one timed operation with a
  128-bit trace id, 64-bit span id, parent link, attributes, and events.
- :class:`Tracer` — creates spans; keeps a per-thread stack so nested
  ``with TRACER.span(...)`` blocks parent automatically; sampling is
  decided once per root span (``sample_ratio``) and inherited by
  children. A non-sampled span is a shared no-op singleton: the unsampled
  hot path is two dict/attr reads and a float compare — O(ns), no id
  generation, no store append.
- :class:`TraceStore` — bounded in-process ring of finished spans keyed
  by trace id (the flight recorder's raw material), exported as
  OTLP-JSON via :meth:`TraceStore.to_otlp` and served on the metrics
  endpoint's ``/traces`` path (``.metrics.MetricsServer``).
- :class:`AdmissionTraces` — per-pod root spans that stitch the
  scheduler extender's *separate* webhook verbs (filter → prioritize →
  bind) into one admission trace.
- **Cross-process propagation**: the extender records its bind span's
  context in the pod annotation ``tpushare.aliyun.com/trace-id``
  (``const.ANN_TRACE_ID``); the device plugin's allocator reads it after
  matching the pod and *adopts* the context
  (:meth:`Tracer.adopt_current_trace`), re-parenting its open span stack
  — so the two processes' spans stitch into one trace with no collector
  in between (``inspect trace <pod>`` merges the two ``/traces``
  endpoints).

The per-pod admission root spans held open across webhook verbs live
inside :class:`AdmissionTraces` (bounded + TTL'd); this module is the
one place allowed to hold spans open across function boundaries — the
``span-leak`` tpulint rule exempts it and requires every other
``start_span`` to be dominated by ``end()``.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from collections import OrderedDict
from typing import Any, Iterator

from .lockrank import make_lock

# Annotation key carrying "trace_id:span_id" across the extender ->
# plugin process boundary (duplicated in const.ANN_TRACE_ID; const
# imports nothing and this module must stay import-light, so the string
# lives in both — test_tracing pins they agree).
TRACE_ANNOTATION = "tpushare.aliyun.com/trace-id"

STATUS_OK = "ok"
STATUS_ERROR = "error"


class SpanContext:
    """Immutable (trace id, span id, sampled) triple — what crosses a
    process boundary."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def encode(self) -> str:
        """Wire form for the pod annotation: ``<trace_id>:<span_id>``."""
        return f"{self.trace_id}:{self.span_id}"

    def __repr__(self) -> str:
        return f"SpanContext({self.trace_id}, {self.span_id})"


def parse_context(value: str | None) -> SpanContext | None:
    """Parse the annotation form; tolerant of a bare trace id and of
    garbage (the annotation is user-writable — a garbled value must not
    break admission, just break stitching)."""
    if not value:
        return None
    head, _, tail = value.partition(":")
    trace_id = head.strip()
    span_id = tail.strip()
    if not _is_hex(trace_id, 32):
        return None
    if span_id and not _is_hex(span_id, 16):
        span_id = ""
    return SpanContext(trace_id, span_id, sampled=True)


def _is_hex(s: str, width: int) -> bool:
    if len(s) != width:
        return False
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


# Span/trace ids need uniqueness, not cryptographic strength — and
# os.urandom is a syscall (~15us under some container kernels), which at
# several spans per admission is real hot-path money. One PRNG seeded
# from the OS once; getrandbits is a single C call, atomic under the GIL.
_ID_RNG = random.Random(os.urandom(16))


def _new_trace_id() -> str:
    return f"{_ID_RNG.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_ID_RNG.getrandbits(64):016x}"


class Span:
    """One timed operation. Mutation methods are no-ops on non-recording
    spans, so call sites never branch on sampling themselves."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attributes", "events", "status", "_recording", "_store",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: str = "",
        start_ns: int | None = None,
        attributes: dict[str, Any] | None = None,
        recording: bool = True,
        store: "TraceStore | None" = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.time_ns() if start_ns is None else start_ns
        self.end_ns = 0
        self.attributes: dict[str, Any] = dict(attributes or {})
        self.events: list[tuple[str, int, dict[str, Any]]] = []
        self.status = STATUS_OK
        self._recording = recording
        self._store = store

    @property
    def recording(self) -> bool:
        return self._recording

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, sampled=self._recording)

    def set_attribute(self, key: str, value: Any) -> None:
        if self._recording:
            self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        if self._recording:
            self.events.append((name, time.time_ns(), dict(attributes)))

    def end(self, status: str | None = None, end_ns: int | None = None) -> None:
        """Finish the span (idempotent) and hand it to the store."""
        if not self._recording or self.end_ns:
            return
        if status is not None:
            self.status = status
        self.end_ns = time.time_ns() if end_ns is None else end_ns
        if self._store is not None:
            self._store.add(self)

    @property
    def duration_ms(self) -> float:
        if not self.end_ns:
            return 0.0
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self) -> dict[str, Any]:
        """Flat dict form (the CLI's working format)."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "status": self.status,
            "attributes": dict(self.attributes),
            "events": [
                {"name": n, "time_ns": t, "attributes": a}
                for n, t, a in self.events
            ],
        }


class _NoopSpan(Span):
    """Shared singleton for unsampled work: every method returns
    immediately, nothing allocates per call."""

    def __init__(self) -> None:
        super().__init__("noop", "", "", recording=False)

    def end(self, status: str | None = None, end_ns: int | None = None) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class TraceStore:
    """Bounded in-process ring of finished spans, keyed by trace id.

    Insertion order doubles as eviction order (oldest trace evicted
    whole when ``max_traces`` is exceeded) — exactly the "last N
    admission traces" the flight recorder dumps. Pure memory under its
    lock; exports snapshot first and serialize outside."""

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512) -> None:
        self._lock = make_lock("tracing.store")
        self._max_traces = max_traces
        self._max_spans = max_spans_per_trace
        self._traces: OrderedDict[str, list[Span]] = OrderedDict()
        self._dropped = 0

    def add(self, span: Span) -> None:
        if not span.trace_id:
            return
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = []
                self._traces[span.trace_id] = spans
                while len(self._traces) > self._max_traces:
                    self._traces.popitem(last=False)
                    self._dropped += 1
            if len(spans) < self._max_spans:
                spans.append(span)

    def trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def snapshot(self) -> dict[str, list[Span]]:
        with self._lock:
            return {tid: list(spans) for tid, spans in self._traces.items()}

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._dropped = 0

    def to_otlp(
        self, trace_id: str | None = None, service: str = "tpushare"
    ) -> dict[str, Any]:
        """OTLP/JSON-shaped export (the ``/traces`` endpoint body): the
        ``resourceSpans``/``scopeSpans``/``spans`` nesting an OTLP
        consumer expects, attributes as keyed ``stringValue``s."""
        if trace_id is not None:
            spans = self.trace(trace_id)
        else:
            spans = [s for ss in self.snapshot().values() for s in ss]
        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [_otlp_attr("service.name", service)]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "gpushare_device_plugin_tpu.tracing"},
                            "spans": [_otlp_span(s) for s in spans],
                        }
                    ],
                }
            ]
        }


def _otlp_attr(key: str, value: Any) -> dict[str, Any]:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def _otlp_span(span: Span) -> dict[str, Any]:
    return {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "parentSpanId": span.parent_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(span.start_ns),
        "endTimeUnixNano": str(span.end_ns),
        "attributes": [_otlp_attr(k, v) for k, v in span.attributes.items()],
        "events": [
            {
                "timeUnixNano": str(t),
                "name": n,
                "attributes": [_otlp_attr(k, v) for k, v in a.items()],
            }
            for n, t, a in span.events
        ],
        "status": {"code": 2 if span.status == STATUS_ERROR else 1},
    }


def _otlp_value(value: dict[str, Any]) -> Any:
    for k in ("stringValue", "boolValue", "doubleValue"):
        if k in value:
            return value[k]
    if "intValue" in value:
        try:
            return int(value["intValue"])
        except (TypeError, ValueError):
            return value["intValue"]
    return None


def spans_from_otlp(doc: dict[str, Any]) -> list[dict[str, Any]]:
    """Flatten an OTLP-JSON document back to the flat-dict span form
    (the inspect CLI consumes ``/traces`` bodies through this)."""
    out: list[dict[str, Any]] = []
    for rs in doc.get("resourceSpans", ()):
        for ss in rs.get("scopeSpans", ()):
            for sp in ss.get("spans", ()):
                out.append(
                    {
                        "trace_id": sp.get("traceId", ""),
                        "span_id": sp.get("spanId", ""),
                        "parent_id": sp.get("parentSpanId", ""),
                        "name": sp.get("name", ""),
                        "start_ns": int(sp.get("startTimeUnixNano", 0) or 0),
                        "end_ns": int(sp.get("endTimeUnixNano", 0) or 0),
                        "status": (
                            STATUS_ERROR
                            if sp.get("status", {}).get("code") == 2
                            else STATUS_OK
                        ),
                        "attributes": {
                            a["key"]: _otlp_value(a.get("value", {}))
                            for a in sp.get("attributes", ())
                            if "key" in a
                        },
                        "events": [
                            {
                                "name": e.get("name", ""),
                                "time_ns": int(e.get("timeUnixNano", 0) or 0),
                                "attributes": {
                                    a["key"]: _otlp_value(a.get("value", {}))
                                    for a in e.get("attributes", ())
                                    if "key" in a
                                },
                            }
                            for e in sp.get("events", ())
                        ],
                    }
                )
    return out


class Tracer:
    """Creates spans against one store with one sampling policy.

    Thread-local span stack: ``with TRACER.span(...)`` pushes, nested
    spans parent automatically, and the stack is what
    :meth:`adopt_current_trace` re-parents when the allocator discovers
    (mid-admission, after the pod match) that the extender already
    started this pod's trace."""

    def __init__(
        self,
        store: TraceStore | None = None,
        sample_ratio: float = 1.0,
        service: str = "tpushare",
    ) -> None:
        self._store = store if store is not None else TraceStore()
        self._ratio = float(sample_ratio)
        # Per-tier overrides of the root sampling ratio (the daemon's
        # --trace-sample-critical / --trace-sample-besteffort flags):
        # best-effort churn can be down-sampled without losing
        # critical-tier traces. Tiers not listed inherit the default.
        self._tier_ratios: dict[str, float] = {}
        self.service = service
        self._tls = threading.local()

    # --- configuration ----------------------------------------------------

    @property
    def store(self) -> TraceStore:
        return self._store

    @property
    def sample_ratio(self) -> float:
        return self._ratio

    def tier_sample_ratio(self, tier: str | None) -> float:
        """The effective root-sampling ratio for ``tier`` (the default
        ratio when the tier has no override or is None)."""
        if tier is None:
            return self._ratio
        return self._tier_ratios.get(tier, self._ratio)

    def configure(
        self,
        sample_ratio: float | None = None,
        tier_ratios: dict[str, float] | None = None,
    ) -> None:
        """Runtime reconfiguration (the daemon's ``--trace-sample`` flag,
        the bench's ``--no-trace``). ``tier_ratios`` REPLACES the
        per-tier override table when given (pass ``{}`` to clear); the
        default ratio still governs tiers without an entry — and every
        root created without a tier — so the no-override configuration
        behaves exactly as before the flags existed."""
        if sample_ratio is not None:
            self._ratio = float(sample_ratio)
        if tier_ratios is not None:
            self._tier_ratios = {
                str(t): float(r) for t, r in tier_ratios.items()
            }

    # --- span stack -------------------------------------------------------

    def _stack(self) -> list[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def current_span(self) -> Span | None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return None
        return stack[-1]

    def current_context(self) -> SpanContext | None:
        """The innermost *recording* span's context, or None — what log
        correlation and histogram exemplars stamp."""
        span = self.current_span()
        if span is None or not span.recording:
            return None
        return span.context()

    def _sampled_root(self, tier: str | None = None) -> bool:
        ratio = self.tier_sample_ratio(tier)
        if ratio >= 1.0:
            return True
        if ratio <= 0.0:
            return False
        return random.random() < ratio

    # --- span creation ----------------------------------------------------

    def start_span(
        self,
        name: str,
        parent: SpanContext | Span | None = None,
        attributes: dict[str, Any] | None = None,
        child_only: bool = False,
    ) -> Span:
        """Create a span. ``parent`` may be a Span, a SpanContext, or
        None (None: parent under the thread's current span, else start a
        new root). ``child_only`` spans never start a trace of their own
        — without a recording parent they are the no-op singleton (used
        by deep helpers like the WAL batch wait, which would otherwise
        mint orphan root traces when driven outside an admission).

        Callers of this method MUST end the span on every path — the
        ``span-leak`` tpulint rule enforces it; prefer :meth:`span`.
        """
        if isinstance(parent, Span):
            if not parent.recording:
                return NOOP_SPAN  # inherit the parent's unsampled decision
            parent = parent.context()
        if parent is None:
            cur = self.current_span()
            if cur is not None:
                # The root's sampling decision is inherited DOWN the open
                # stack: under an unsampled span, nested spans must not
                # re-roll and mint orphan root traces.
                if not cur.recording:
                    return NOOP_SPAN
                parent = cur.context()
            elif child_only:
                return NOOP_SPAN
        if parent is not None:
            if not parent.sampled:
                return NOOP_SPAN
            return Span(
                name,
                trace_id=parent.trace_id,
                span_id=_new_span_id(),
                parent_id=parent.span_id,
                attributes=attributes,
                store=self._store,
            )
        if not self._sampled_root():
            return NOOP_SPAN
        return Span(
            name,
            trace_id=_new_trace_id(),
            span_id=_new_span_id(),
            attributes=attributes,
            store=self._store,
        )

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: SpanContext | Span | None = None,
        attributes: dict[str, Any] | None = None,
        child_only: bool = False,
    ) -> Iterator[Span]:
        """``with TRACER.span("allocator.place") as sp:`` — the span is
        pushed as the thread's current (children parent under it), ended
        on exit, marked ``error`` with the exception repr when the body
        raises."""
        sp = self.start_span(
            name, parent=parent, attributes=attributes, child_only=child_only
        )
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set_attribute("error", repr(e))
            sp.end(STATUS_ERROR)
            raise
        finally:
            # pop by identity: an adopting callee may have replaced ids,
            # but the object is the same
            if stack and stack[-1] is sp:
                stack.pop()
            elif sp in stack:
                stack.remove(sp)
        sp.end()

    def record_span(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        parent: SpanContext | None = None,
        attributes: dict[str, Any] | None = None,
        status: str = STATUS_OK,
        events: list[tuple[str, int, dict[str, Any]]] | None = None,
        tier: str | None = None,
    ) -> SpanContext | None:
        """Create an already-finished span from explicit timestamps (the
        serving engine reconstructs each request's timeline at retire
        time — zero tracing work on the per-token hot loop). Returns the
        span's context for building children, or None when unsampled.
        ``tier`` selects a per-tier root sampling override when this
        span starts a new trace (``configure(tier_ratios=...)``)."""
        if parent is None:
            if not self._sampled_root(tier):
                return None
            trace_id = _new_trace_id()
            parent_id = ""
        else:
            if not parent.sampled:
                return None
            trace_id = parent.trace_id
            parent_id = parent.span_id
        sp = Span(
            name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            start_ns=start_ns,
            attributes=attributes,
            store=self._store,
        )
        if events:
            sp.events.extend(events)
        sp.status = status
        sp.end_ns = end_ns
        self._store.add(sp)
        return sp.context()

    # --- cross-process adoption -------------------------------------------

    def adopt_current_trace(self, ctx: SpanContext | None) -> bool:
        """Re-parent this thread's OPEN span stack under ``ctx``.

        The device plugin's Allocate starts its span before it knows
        which pod it is admitting; once the pod is matched and its
        ``tpushare.aliyun.com/trace-id`` annotation read, adoption
        rewrites the open spans' trace ids and links the outermost one
        under the extender's bind span — one stitched trace. Spans that
        already ended keep their original ids (adopt early). No-op on
        None/unsampled contexts. Returns True when anything changed."""
        if ctx is None or not ctx.sampled or not ctx.trace_id:
            return False
        stack = [s for s in self._stack() if s.recording and not s.end_ns]
        if not stack:
            return False
        stack[0].parent_id = ctx.span_id
        for sp in stack:
            sp.trace_id = ctx.trace_id
        return True


class AdmissionTraces:
    """Per-pod admission root spans: the glue that makes the extender's
    separate filter/prioritize/bind webhook calls one trace.

    ``root(ns, name)`` starts (or returns) the pod's admission root span
    context; each verb then parents its own span under it. ``finish``
    ends the root. Bounded and TTL'd: a pod the scheduler filtered but
    never bound must not pin a span forever — stale roots are ended with
    status ``unfinished`` on eviction."""

    def __init__(
        self,
        tracer: Tracer,
        max_pods: int = 512,
        ttl_s: float = 300.0,
    ) -> None:
        self._tracer = tracer
        self._max = max_pods
        self._ttl = ttl_s
        self._lock = make_lock("tracing.admissions")
        self._roots: OrderedDict[tuple[str, str], tuple[Span, float]] = (
            OrderedDict()
        )

    def root(
        self, namespace: str, name: str, attributes: dict[str, Any] | None = None
    ) -> SpanContext | None:
        """The pod's admission root context, created on first touch.
        Returns None when the trace was not sampled (every verb's span
        then no-ops)."""
        key = (namespace, name)
        now = time.monotonic()
        evicted: list[Span] = []
        with self._lock:
            entry = self._roots.get(key)
            if entry is not None and now - entry[1] <= self._ttl:
                # recency touch: a pod actively going filter->prioritize
                # ->bind must not be the one max_pods pressure evicts
                self._roots.move_to_end(key)
                span = entry[0]
            else:
                if entry is not None:  # stale: end the old incarnation
                    evicted.append(entry[0])
                    self._roots.pop(key, None)
                span = self._tracer.start_span(
                    "admission",
                    parent=None,
                    attributes={"pod": f"{namespace}/{name}", **(attributes or {})},
                )
                if span.recording:
                    self._roots[key] = (span, now)
                while len(self._roots) > self._max:
                    _, (old, _stamp) = self._roots.popitem(last=False)
                    evicted.append(old)
        for old in evicted:
            old.end("unfinished")
        if not span.recording:
            return None
        return span.context()

    def finish(
        self, namespace: str, name: str, status: str = STATUS_OK
    ) -> None:
        with self._lock:
            entry = self._roots.pop((namespace, name), None)
        if entry is not None:
            entry[0].end(status)

    def open_count(self) -> int:
        with self._lock:
            return len(self._roots)


# Process-wide defaults, mirroring utils.metrics.REGISTRY / utils.faults
# .FAULTS: one store, one tracer, one admission registry per process.
STORE = TraceStore()
TRACER = Tracer(store=STORE)
ADMISSIONS = AdmissionTraces(TRACER)


def current_trace_ids() -> tuple[str, str] | None:
    """(trace_id, span_id) of the innermost recording span on this
    thread, or None — the log-correlation / exemplar hook."""
    ctx = TRACER.current_context()
    if ctx is None:
        return None
    return ctx.trace_id, ctx.span_id
