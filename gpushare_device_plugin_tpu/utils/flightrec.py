"""Crash/postmortem flight recorder: the last N admission traces plus
recent log records, dumped to disk when something dies.

The WAL (allocator/checkpoint.py) makes a crash *recoverable*; this
makes it *explainable*. A bounded ring of recent log records (fed by a
logging handler, each stamped with the trace/span ids that were current
when it was emitted) rides next to the trace store's last-N admission
traces; :meth:`FlightRecorder.dump` snapshots both and writes one JSON
file. Dump triggers, all wired by :meth:`FlightRecorder.install` +
``TpuShareManager.install_signal_handlers``:

- **SIGUSR1** — operator-requested postmortem of a live daemon
  ("why are admissions slow right now"), the trace analog of SIGQUIT's
  stack dump.
- **fatal daemon exit** — ``utils.log.Logger.fatal`` runs the registered
  on-fatal hooks before raising SystemExit.
- **fault-injection crash sites** — ``utils.faults`` fires the crash
  hook just before raising ``SimulatedCrash``, so the restart-recovery
  suite's kill-at-every-journal-step runs leave a flight record exactly
  where a production SIGKILL would have (when a recorder is installed).

Dump format (one JSON document)::

    {"reason": "SIGUSR1", "time_unix": ..., "pid": ...,
     "service": "tpushare", "trace_count": N, "dropped_traces": ...,
     "traces": {<OTLP-JSON, tracing.TraceStore.to_otlp>},
     "logs": [{"time_unix", "level", "logger", "message",
               "trace_id", "span_id"}, ...]}

``kubectl-inspect-tpushare flightrecord <file>`` renders it.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Any

from . import tracing
from .lockrank import make_lock

DEFAULT_MAX_LOGS = 512
# Rotation bound: only the newest K dump files are kept in the dump
# directory. Repeated SIGUSR1 postmortems / chaos-suite crash loops used
# to grow the directory without bound — the flight recorder is a ring in
# memory, so its disk footprint is a ring too. 0 disables.
DEFAULT_MAX_DUMPS = 16
_DUMP_PREFIX = "tpushare-flightrec-"


class _RingHandler(logging.Handler):
    """Bounded log-record ring. Formatting happens at emit time (records
    hold live args otherwise) and each entry is stamped with the ids of
    the span that was current on the emitting thread."""

    def __init__(self, recorder: "FlightRecorder") -> None:
        super().__init__(level=logging.DEBUG)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            message = record.getMessage()
        except (TypeError, ValueError):  # mismatched format args
            message = str(record.msg)
        ids = tracing.current_trace_ids()
        self._recorder._append_log(
            {
                "time_unix": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": message,
                "trace_id": ids[0] if ids else "",
                "span_id": ids[1] if ids else "",
            }
        )


class FlightRecorder:
    """Owns the log ring and the dump path; one per process (the module
    singleton :data:`FLIGHT`)."""

    def __init__(
        self,
        store: tracing.TraceStore | None = None,
        max_logs: int = DEFAULT_MAX_LOGS,
        max_dumps: int = DEFAULT_MAX_DUMPS,
    ) -> None:
        self._store = store if store is not None else tracing.STORE
        self._lock = make_lock("flightrec.ring")
        self._logs: deque[dict[str, Any]] = deque(maxlen=max_logs)
        self._dir = ""
        self._installed = False
        self._dumps = 0
        self._keep = max_dumps
        self._handler: _RingHandler | None = None

    # --- wiring -----------------------------------------------------------

    def install(
        self,
        directory: str,
        logger: logging.Logger | None = None,
        max_dumps: int | None = None,
    ) -> None:
        """Attach the log ring to ``logger`` (root by default) and
        register the fatal-exit and injected-crash dump hooks.
        Idempotent; re-install just updates the directory (and the
        rotation bound, when given — ``max_dumps`` keeps the newest K
        dump files on disk, 0 disables rotation)."""
        self._dir = directory
        if max_dumps is not None:
            self._keep = max_dumps
        if self._installed:
            return
        self._installed = True
        self._handler = _RingHandler(self)
        (logger or logging.getLogger()).addHandler(self._handler)
        from . import faults, log

        log.on_fatal(lambda reason: self.dump(f"fatal:{reason}"))
        faults.FAULTS.set_crash_hook(lambda point: self.dump(f"crash:{point}"))

    def uninstall(self, logger: logging.Logger | None = None) -> None:
        """Detach the ring handler and clear the hooks (tests)."""
        if self._handler is not None:
            (logger or logging.getLogger()).removeHandler(self._handler)
            self._handler = None
        from . import faults, log

        faults.FAULTS.set_crash_hook(None)
        log.clear_fatal_hooks()
        self._installed = False

    def install_signal_handler(self, signum: int | None = None) -> bool:
        """SIGUSR1 -> dump. Returns False where signals are unavailable
        (non-main thread, platforms without SIGUSR1).

        The handler only SPAWNS the dump: Python signal handlers run on
        the main thread between bytecodes, and the main thread may be
        holding the (non-reentrant) ring/store lock at that instant —
        an inline dump would self-deadlock the daemon. A worker thread
        just waits its turn for the locks like any other reader."""
        import signal
        import threading

        if signum is None:
            signum = getattr(signal, "SIGUSR1", None)
            if signum is None:
                return False

        def handler(*_: object) -> None:
            threading.Thread(
                target=self.dump, args=("SIGUSR1",),
                name="flightrec-dump", daemon=True,
            ).start()

        try:
            signal.signal(signum, handler)
            return True
        except (OSError, ValueError):  # not main thread / bad signum
            return False

    # --- ring -------------------------------------------------------------

    def _append_log(self, entry: dict[str, Any]) -> None:
        with self._lock:
            self._logs.append(entry)

    def recent_logs(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._logs)

    @property
    def dump_count(self) -> int:
        return self._dumps

    # --- dump -------------------------------------------------------------

    def snapshot(self, reason: str) -> dict[str, Any]:
        """The dump document, built from snapshots (no I/O under locks).
        Includes the cluster-state timeline ring (utils/timeline.py) so
        the postmortem carries the minutes of utilization/fragmentation/
        queue-depth/SLO-burn history *before* the crash, not just the
        instant of death."""
        from .timeline import TIMELINE

        trace_ids = self._store.trace_ids()
        return {
            "reason": reason,
            "time_unix": time.time(),
            "pid": os.getpid(),
            "service": "tpushare",
            "trace_count": len(trace_ids),
            "dropped_traces": self._store.dropped(),
            "traces": self._store.to_otlp(),
            "logs": self.recent_logs(),
            "timeline": TIMELINE.to_doc(),
        }

    def dump(self, reason: str) -> str:
        """Write one flight record; returns its path ('' when disabled
        or the write failed — a dying daemon must not die harder because
        the dump disk is sick)."""
        if not self._dir:
            return ""
        doc = self.snapshot(reason)
        slug = "".join(c if c.isalnum() else "-" for c in reason)[:48]
        path = os.path.join(
            self._dir, f"{_DUMP_PREFIX}{int(time.time())}-{slug}.json"
        )
        try:
            os.makedirs(self._dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        except OSError as e:
            logging.getLogger("utils.flightrec").warning(
                "flight-record dump failed: %s", e
            )
            return ""
        self._rotate(keep_path=path)
        with self._lock:  # dumps can come from the signal-spawned thread
            self._dumps += 1
        logging.getLogger("utils.flightrec").info(
            "flight record (%s): %s", reason, path
        )
        return path

    def _rotate(self, keep_path: str = "") -> None:
        """Prune the dump directory to the newest ``max_dumps`` files
        (the one just written always survives, whatever its timestamp —
        a skewed clock must not make a fresh postmortem the 'oldest').
        Best-effort: a sick dump disk must not hurt the dumper."""
        if self._keep <= 0 or not self._dir:
            return
        try:
            entries = []
            with os.scandir(self._dir) as it:
                for entry in it:
                    if not entry.name.startswith(_DUMP_PREFIX):
                        continue
                    if not entry.name.endswith(".json"):
                        continue
                    try:
                        entries.append((entry.stat().st_mtime, entry.path))
                    except OSError:
                        continue
            entries.sort()  # oldest first
            excess = len(entries) - self._keep
            for _mtime, victim in entries:
                if excess <= 0:
                    break
                if keep_path and os.path.abspath(victim) == os.path.abspath(
                    keep_path
                ):
                    continue
                try:
                    os.unlink(victim)
                    excess -= 1
                except OSError:
                    continue
        except OSError as e:
            logging.getLogger("utils.flightrec").warning(
                "flight-record rotation failed: %s", e
            )


def load_dump(path: str) -> dict[str, Any]:
    """Read a flight-record file (the inspect CLI's half)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a flight-record document")
    return doc


# Process-wide recorder, mirroring tracing.STORE / metrics.REGISTRY.
FLIGHT = FlightRecorder()
