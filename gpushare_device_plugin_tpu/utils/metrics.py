"""Prometheus-exposition metrics for the daemon and extender (stdlib only).

The reference has no metrics at all (SURVEY.md section 5: glog only; the
observability story is the inspect CLI reading apiserver state). This adds
the operational half operators actually scrape: a tiny text-format
`/metrics` endpoint — counters, gauges, and fixed-bucket histograms over
the hot paths — with zero dependencies (no prometheus_client in the
image; the exposition text format is trivial to emit by hand).

Thread-safe by a single lock per registry; all operations are O(1) and
the Allocate-path overhead is one dict update + lock, microseconds
against a ~1.4 ms p50.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Iterator
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import tracing
from .lockrank import make_lock
from .metric_catalog import BUILD_INFO as BUILD_INFO_GAUGE

# Latency buckets (seconds): 0.5ms .. 10s, roughly log-spaced around the
# observed allocate p50 of ~1.4ms.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

# Lock-wait buckets (seconds): the sharded allocator's locks guard pure
# in-memory work, so waits should live in the low-microsecond rows; the
# tail rows exist to make contention regressions (I/O creeping back under
# a lock) jump out of a scrape.
LOCK_WAIT_BUCKETS = (
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
)


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or a scraper mis-parses the line
    (exposition format 0.0.4; pod names and error strings end up in
    labels, so this is not theoretical)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = make_lock("metrics.registry")
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # name -> (buckets, {labels -> [counts..., sum, count]})
        self._hists: dict[str, tuple[tuple[float, ...], dict]] = {}
        self._help: dict[str, tuple[str, str]] = {}  # name -> (type, help)
        # Exemplars: (name, labels) -> {bucket index -> (trace_id, value,
        # unix ts)}; bucket index len(buckets) is +Inf. Recorded when an
        # observation happens inside a sampled trace span, so a scrape's
        # latency buckets link straight to the admission trace that put
        # mass there (rendered in the OpenMetrics exposition only — the
        # classic 0.0.4 text format has no exemplar syntax).
        self._exemplars: dict[tuple[str, tuple], dict[int, tuple[str, float, float]]] = {}

    def _describe(self, name: str, mtype: str, help_text: str) -> None:
        self._help.setdefault(name, (mtype, help_text))

    def counter_inc(
        self, name: str, help_text: str = "", value: float = 1.0,
        **labels: str,
    ) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._describe(name, "counter", help_text)
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge_set(
        self, name: str, value: float, help_text: str = "", **labels: str
    ) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._describe(name, "gauge", help_text)
            self._gauges[key] = float(value)

    def observe(
        self, name: str, seconds: float, help_text: str = "",
        buckets: tuple[float, ...] = DEFAULT_BUCKETS, **labels: str,
    ) -> None:
        lkey = tuple(sorted(labels.items()))
        # Read the current trace OUTSIDE the registry lock (one TLS read;
        # None on the unsampled/untraced fast path).
        ids = tracing.current_trace_ids()
        with self._lock:
            self._describe(name, "histogram", help_text)
            bks, series = self._hists.setdefault(name, (buckets, {}))
            row = series.setdefault(lkey, [0] * len(bks) + [0.0, 0])
            bucket_i = len(bks)  # +Inf unless a finite bucket catches it
            for i, b in enumerate(bks):
                if seconds <= b:
                    row[i] += 1
                    bucket_i = min(bucket_i, i)
            row[-2] += seconds
            row[-1] += 1
            if ids is not None:
                self._exemplars.setdefault((name, lkey), {})[bucket_i] = (
                    ids[0], seconds, time.time(),
                )

    # --- programmatic readers (bench / tests) ---------------------------

    def counter_value(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def gauge_value(self, name: str, **labels: str) -> float | None:
        """One labeled gauge's current value; None when never set."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key)

    def gauge_series(
        self, name: str
    ) -> dict[tuple[tuple[str, str], ...], float]:
        """Every labeled series of one gauge family: sorted label tuple
        -> value (the interference detector enumerates the per-pod step
        gauges through this)."""
        with self._lock:
            return {
                labels: val
                for (n, labels), val in self._gauges.items()
                if n == name
            }

    def histogram_stats(self, name: str, **labels: str) -> tuple[int, float]:
        """(observation count, sum) for one labeled histogram series;
        (0, 0.0) when it has never been observed."""
        lkey = tuple(sorted(labels.items()))
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                return 0, 0.0
            row = hist[1].get(lkey)
            if row is None:
                return 0, 0.0
            return row[-1], row[-2]

    def histogram_quantile(self, name: str, q: float, **labels: str) -> float | None:
        """Approximate quantile from the fixed buckets (linear within the
        winning bucket, like PromQL's histogram_quantile). None when the
        series has no observations."""
        lkey = tuple(sorted(labels.items()))
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                return None
            bks, series = hist
            row = series.get(lkey)
            if row is None or row[-1] == 0:
                return None
            total = row[-1]
            rank = q * total
            prev_count, prev_bound = 0, 0.0
            for i, bound in enumerate(bks):
                if row[i] >= rank:
                    in_bucket = row[i] - prev_count
                    if in_bucket <= 0:
                        return bound
                    frac = (rank - prev_count) / in_bucket
                    return prev_bound + (bound - prev_bound) * frac
                prev_count, prev_bound = row[i], bound
            return bks[-1]  # beyond the last bucket: clamp like PromQL

    def exemplar(self, name: str, **labels: str) -> dict[int, tuple[str, float, float]]:
        """Bucket-index -> (trace_id, value, ts) exemplars for one series
        (test/debug reader)."""
        lkey = tuple(sorted(labels.items()))
        with self._lock:
            return dict(self._exemplars.get((name, lkey), {}))

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition.

        Default: classic text format 0.0.4 (no exemplar syntax exists
        there). ``openmetrics=True``: the same families with OpenMetrics
        exemplar suffixes on histogram bucket lines — ``# {trace_id=
        "..."} value timestamp`` — plus the ``# EOF`` terminator; served
        when a scraper negotiates ``application/openmetrics-text``."""
        out: list[str] = []
        with self._lock:
            seen: set[str] = set()

            def header(name: str):
                if name in seen:
                    return
                seen.add(name)
                mtype, help_text = self._help.get(name, ("untyped", ""))
                if openmetrics and mtype == "untyped":
                    mtype = "unknown"  # OM's spelling of untyped
                family = name
                if openmetrics and mtype == "counter" and name.endswith("_total"):
                    # OpenMetrics names the FAMILY without the _total
                    # suffix (samples keep it); a strict OM parser —
                    # which modern Prometheus negotiates by default —
                    # rejects the whole scrape otherwise.
                    family = name[: -len("_total")]
                if help_text:
                    out.append(f"# HELP {family} {help_text}")
                out.append(f"# TYPE {family} {mtype}")

            for (name, labels), val in sorted(self._counters.items()):
                header(name)
                out.append(f"{name}{_fmt_labels(labels)} {val:g}")
            for (name, labels), val in sorted(self._gauges.items()):
                header(name)
                out.append(f"{name}{_fmt_labels(labels)} {val:g}")
            for name, (bks, series) in sorted(self._hists.items()):
                header(name)
                for lkey, row in sorted(series.items()):
                    exemplars = (
                        self._exemplars.get((name, lkey), {})
                        if openmetrics else {}
                    )

                    def _ex(i: int) -> str:
                        ex = exemplars.get(i)
                        if ex is None:
                            return ""
                        tid, value, ts = ex
                        return (
                            f' # {{trace_id="{tid}"}} {value:g} {ts:.3f}'
                        )

                    cum = 0
                    for i, b in enumerate(bks):
                        cum = row[i]
                        lbl = _fmt_labels(lkey + (("le", f"{b:g}"),))
                        out.append(f"{name}_bucket{lbl} {cum}{_ex(i)}")
                    lbl = _fmt_labels(lkey + (("le", "+Inf"),))
                    out.append(f"{name}_bucket{lbl} {row[-1]}{_ex(len(bks))}")
                    out.append(f"{name}_sum{_fmt_labels(lkey)} {row[-2]:g}")
                    out.append(f"{name}_count{_fmt_labels(lkey)} {row[-1]}")
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


# Process-wide default registry (the daemon's single plugin process).
REGISTRY = MetricsRegistry()

_BUILD_FACTS: dict[str, str] | None = None  # computed once per process


def _build_facts() -> dict[str, str]:
    global _BUILD_FACTS
    if _BUILD_FACTS is None:
        import os
        import platform

        from .. import __version__

        try:
            from importlib.metadata import version as _pkg_version

            jax_version = _pkg_version("jax")
        except Exception:  # noqa: BLE001 — images without jax
            jax_version = "none"
        _BUILD_FACTS = {
            "version": __version__,
            "git_rev": os.environ.get("TPUSHARE_GIT_REV", "unknown"),
            "python": platform.python_version(),
            "jax": jax_version,
        }
    return _BUILD_FACTS


def publish_build_info(
    component: str, registry: MetricsRegistry | None = None
) -> dict[str, str]:
    """Export the ``tpushare_build_info`` gauge (value 1; the facts ride
    the labels, Prometheus convention) for one component: package
    version, git revision (baked into the image as ``TPUSHARE_GIT_REV``;
    containers have no .git), python and jax versions. Returns the label
    set so CLIs can render the same header. jax's version is read from
    package metadata, NOT by importing jax — the control-plane processes
    stay jax-free; the facts are computed once per process."""
    labels = {"component": component, **_build_facts()}
    (registry or REGISTRY).gauge_set(
        BUILD_INFO_GAUGE, 1.0,
        "Build/runtime identity (value is always 1; the labels carry "
        "version, git revision, python and jax versions)",
        **labels,
    )
    return labels


@contextlib.contextmanager
def timed_acquire(
    mutex: Any, name: str, help_text: str = "",
    registry: MetricsRegistry | None = None, **labels: str,
) -> Iterator[Any]:
    """``with timed_acquire(mutex, metric):`` — acquire ``mutex``, recording
    the time spent *waiting* for it (not the hold time) in a histogram.
    The allocator's lock-wait visibility: a healthy sharded hot path shows
    near-zero waits; contention shows up as mass in the upper buckets.
    (First param is not named ``lock`` so a ``lock=...`` metric label can
    pass through ``**labels``.)"""
    t0 = time.perf_counter()
    mutex.acquire()
    (registry or REGISTRY).observe(
        name, time.perf_counter() - t0, help_text,
        buckets=LOCK_WAIT_BUCKETS, **labels,
    )
    try:
        yield mutex
    finally:
        mutex.release()


class MetricsServer:
    """Minimal /metrics + /traces + /decisions + /timeline + /healthz +
    /readyz HTTP endpoint (off by default; the daemon enables it with
    --metrics-port).

    ``/metrics`` negotiates the exposition: classic text format 0.0.4 by
    default, OpenMetrics (with histogram exemplars linking latency
    buckets to trace ids) when the scraper's Accept header names
    ``application/openmetrics-text``. ``/traces`` serves the in-process
    trace store as OTLP-JSON (``?trace_id=<id>`` narrows to one trace —
    what ``kubectl-inspect-tpushare trace`` fetches). ``/decisions``
    serves the decision-provenance ring as JSON (``?pod=ns/name`` /
    ``?verb=`` narrow — what ``inspect why`` fetches); ``/timeline``
    serves the cluster-state timeline ring (``inspect timeline``).
    ``/shards`` serves the shard router's shard map (ring ownership,
    per-shard WAL seq + queue depth, 2PC gangs in flight — what
    ``inspect shards`` fetches) when ``shards_doc_fn`` is wired, 404
    otherwise; ``/fleet`` serves the fleet router's replica map, router
    outcomes, scale state and global prefix-hit ratio (what ``inspect
    fleet`` fetches) when ``fleet_doc_fn`` is wired, same default.
    ``/healthz`` is liveness (200 while the server thread
    runs);
    ``/readyz`` consults ``ready_fn`` — 200 when it returns truthy, 503
    otherwise (deploy probes gate on informer sync + WAL replay for the
    extender, plugin registration for the daemon)."""

    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 host: str = "0.0.0.0", port: int = 0,
                 trace_store: "tracing.TraceStore | None" = None,
                 decisions: Any = None,
                 timeline: Any = None,
                 ready_fn: Callable[[], bool] | None = None,
                 shards_doc_fn: Callable[[], dict] | None = None,
                 fleet_doc_fn: Callable[[], dict] | None = None) -> None:
        self._registry = registry
        self._host = host
        self._port = port
        self._store = trace_store if trace_store is not None else tracing.STORE
        if decisions is None:
            from .decisions import DECISIONS

            decisions = DECISIONS
        self._decisions = decisions
        if timeline is None:
            from .timeline import TIMELINE

            timeline = TIMELINE
        self._timeline = timeline
        self._ready_fn = ready_fn
        self._shards_doc_fn = shards_doc_fn
        self._fleet_doc_fn = fleet_doc_fn
        self._server: ThreadingHTTPServer | None = None

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    def start(self) -> "MetricsServer":
        registry = self._registry
        store = self._store
        decisions = self._decisions
        timeline = self._timeline
        ready_fn = self._ready_fn
        shards_doc_fn = self._shards_doc_fn
        fleet_doc_fn = self._fleet_doc_fn

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: object) -> None:  # quiet
                pass

            def do_GET(self) -> None:
                import json as _json
                from urllib.parse import parse_qs, urlparse

                url = urlparse(self.path)
                if url.path == "/metrics":
                    accept = self.headers.get("Accept", "")
                    openmetrics = "application/openmetrics-text" in accept
                    body = registry.render(openmetrics=openmetrics).encode()
                    ctype = (
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8"
                        if openmetrics
                        else "text/plain; version=0.0.4; charset=utf-8"
                    )
                elif url.path == "/traces":
                    q = parse_qs(url.query)
                    tid = (q.get("trace_id") or [None])[0]
                    body = _json.dumps(store.to_otlp(trace_id=tid)).encode()
                    ctype = "application/json"
                elif url.path == "/decisions":
                    q = parse_qs(url.query)
                    doc = decisions.to_doc(
                        pod=(q.get("pod") or [None])[0],
                        verb=(q.get("verb") or [None])[0],
                    )
                    body = _json.dumps(doc).encode()
                    ctype = "application/json"
                elif url.path == "/timeline":
                    body = _json.dumps(timeline.to_doc()).encode()
                    ctype = "application/json"
                elif url.path == "/shards" and shards_doc_fn is not None:
                    body = _json.dumps(shards_doc_fn()).encode()
                    ctype = "application/json"
                elif url.path == "/fleet" and fleet_doc_fn is not None:
                    body = _json.dumps(fleet_doc_fn()).encode()
                    ctype = "application/json"
                elif url.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                elif url.path == "/readyz":
                    try:
                        ready = ready_fn is None or bool(ready_fn())
                    except Exception:  # noqa: BLE001 — not ready, not dead
                        ready = False
                    body = b"ok\n" if ready else b"not ready\n"
                    self.send_response(200 if ready else 503)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        t = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="metrics"
        )
        t.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
