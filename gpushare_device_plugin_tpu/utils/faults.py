"""Fault-injection layer: named injection points with error/latency/flap.

Every boundary where the daemon talks to something that can fail — the
apiserver REST client, the kubelet client, discovery backends, the plugin
gRPC surface — calls ``FAULTS.fire("<point>")``. With nothing armed that is
one dict read and a return, cheap enough to leave in production code; a
test (or the kind e2e via ``TPUSHARE_FAULTS``) arms a point and the next
call through it fails, stalls, or flaps exactly where a real outage would.

Registered points (see docs/robustness.md for the failure-mode matrix):

======================  =====================================================
``apiserver.request``   every unary verb (LIST/GET/PATCH/POST)
``apiserver.watch``     watch-stream establishment
``kubelet.pods``        kubelet ``/pods`` read
``discovery.probe``     inventory (re)build at plugin (re)start
``discovery.watch_health``  health-event stream (supervised loop entry +
                        every mock-backend poll)
``plugin.allocate``     Allocate RPC entry (kubelet-facing)
``checkpoint.begin``    after the WAL begin record is durably on disk
``checkpoint.commit``   after the WAL commit record is durably on disk
``checkpoint.abort``    after the WAL abort record is durably on disk
``checkpoint.wal_queue``  after a record is queued for group commit,
                        BEFORE its durability wait (crash = the batched
                        record that never got fsync'd)
``checkpoint.batch_fsync``  in the group-commit writer, after a batch
                        became durable (crash = records on disk, every
                        caller of the batch dead)
``allocator.post_persist``  after the pod PATCH landed, before the WAL
                        commit record (the mid-window crash site)
``defrag.plan``         after the move's "plan" phase record is durable,
                        before the destination reservation
``defrag.drain``        after the "drain" record is durable, before the
                        engine quiesce/snapshot
``defrag.copy``         after the "copy" record (snapshot included) is
                        durable
``defrag.switch``       after the "switch" record is durable, before the
                        annotation PATCH (the roll-forward boundary)
``defrag.resume``       after the "resume" record is durable, before the
                        destination restore + move commit
``handoff.export``      after the KV handoff's "export" phase record is
                        durable, before the wire payload materializes
``handoff.transfer``    after the "transfer" record is durable, before
                        destination pages stage / page bytes ship
``handoff.import``      after the "import" record is durable, before the
                        decode tier adopts (the roll-forward boundary)
``handoff.commit``      after the "commit" record is durable, before the
                        entry resolves
``scale.cordon``        after the fleet scale-down's "cordon" phase
                        record is durable, before routing stops
``scale.drain``         after the "drain" record (in-flight request rows
                        included) is durable, before the engine quiesce
``scale.migrate``       after the "migrate" record (drained snapshot
                        included) is durable, before the survivor
                        restore (the roll-forward boundary)
``scale.release``       after the "release" record is durable, before
                        the replica leaves the membership
==========================================================================

The ``checkpoint.*`` / ``allocator.post_persist`` / ``defrag.*`` /
``handoff.*`` / ``scale.*`` points
sit immediately *after* each journal step takes durable effect, so arming
them with the ``crash`` mode is the ``crash_after:<site>`` primitive the
restart-recovery and chaos-move suites drive: the process "dies" with the
file/apiserver state exactly as a SIGKILL at that instruction would leave
it (and, via the crash hook, dumps a flight record first).

Modes:

- ``error``:   raise (``FaultError`` by default, or a supplied exception
               factory) on each affected call.
- ``latency``: sleep ``latency_s`` before letting the call proceed.
- ``flap``:    cyclically fail ``fail_n`` calls then pass ``pass_n`` —
               models a control plane that is intermittently reachable.
- ``crash``:   raise ``SimulatedCrash`` — a ``BaseException``, so no
               business-level ``except Exception`` handler (allocator
               rollback, journal abort) can observe it, exactly like a
               process kill. Cleanup that would not survive a real crash
               must not run; in-memory ``finally`` blocks still do, which
               is fine — a restarted daemon has fresh memory anyway.

``times`` bounds how many *firings* a fault affects (then it disarms
itself); ``None`` means until cleared.

Env activation for e2e runs (``cli/daemon.py`` installs at startup)::

    TPUSHARE_FAULTS="apiserver.request=error:5,kubelet.pods=latency:0.2"

grammar: ``point=mode[:arg]`` comma-separated, where ``arg`` is ``times``
for error, seconds for latency, and ``fail_n/pass_n`` for flap
(``flap:2/3`` = fail 2, pass 3, repeat).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Iterator

from .log import get_logger
from .lockrank import make_lock

log = get_logger("utils.faults")

ENV_FAULTS = "TPUSHARE_FAULTS"

POINTS = (
    "apiserver.request",
    "apiserver.watch",
    "kubelet.pods",
    "discovery.probe",
    "discovery.watch_health",
    "plugin.allocate",
    "checkpoint.begin",
    "checkpoint.commit",
    "checkpoint.abort",
    "checkpoint.wal_queue",
    "checkpoint.batch_fsync",
    "allocator.post_persist",
    "defrag.plan",
    "defrag.drain",
    "defrag.copy",
    "defrag.switch",
    "defrag.resume",
    "handoff.export",
    "handoff.transfer",
    "handoff.import",
    "handoff.commit",
    "scale.cordon",
    "scale.drain",
    "scale.migrate",
    "scale.release",
)


class FaultError(ConnectionError):
    """The injected failure. A ``ConnectionError`` so call sites exercise
    exactly the handling a severed control-plane socket would: the
    apiserver client's retry/breaker accounting, the informer's relist
    path, the pod-source fallbacks."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault at {point}")
        self.point = point


class SimulatedCrash(BaseException):
    """Process death, simulated. Deliberately NOT an ``Exception``: every
    business-level handler on the Allocate path (journal abort, claim
    rollback, gRPC error mapping) catches ``Exception`` and would otherwise
    run cleanup a SIGKILL never runs — which is precisely what restart
    recovery must be tested *without*. Only the test harness catches it."""

    def __init__(self, point: str) -> None:
        super().__init__(f"simulated crash at {point}")
        self.point = point


class _Fault:
    def __init__(
        self,
        point: str,
        mode: str,
        *,
        times: int | None,
        error: Callable[[], Exception] | None,
        latency_s: float,
        fail_n: int,
        pass_n: int,
    ):
        if mode not in ("error", "latency", "flap", "crash"):
            raise ValueError(f"unknown fault mode: {mode}")
        self.point = point
        self.mode = mode
        self.times = times
        if error is not None:
            self.error = error
        elif mode == "crash":
            self.error = lambda: SimulatedCrash(point)
        else:
            self.error = lambda: FaultError(point)
        self.latency_s = latency_s
        self.fail_n = max(1, fail_n)
        self.pass_n = max(1, pass_n)
        self.fired = 0  # calls this fault affected
        self._cycle = 0  # flap position

    def apply(self) -> None:
        """Raise/sleep per mode. Returns normally when the fault passes
        this call through (flap pass phase, or budget exhausted)."""
        if self.times is not None and self.fired >= self.times:
            return
        if self.mode == "flap":
            pos = self._cycle
            self._cycle = (self._cycle + 1) % (self.fail_n + self.pass_n)
            if pos >= self.fail_n:
                return  # pass phase
            self.fired += 1
            raise self.error()
        self.fired += 1
        if self.mode == "latency":
            time.sleep(self.latency_s)
            return
        raise self.error()


class FaultRegistry:
    """Process-wide named injection points. Thread-safe; ``fire`` on an
    unarmed point is one dict read."""

    def __init__(self) -> None:
        self._lock = make_lock("faults.registry")
        self._faults: dict[str, _Fault] = {}
        # Flight-recorder hook: called (outside the registry lock, the
        # dump does file I/O) with the point name just before a crash-
        # mode fault raises SimulatedCrash — so injected kills leave the
        # same postmortem a production SIGKILL site would.
        self._crash_hook: Callable[[str], Any] | None = None
        # Model-checker hook (tools/tpumc): every fire() site is a
        # protocol decision point — the checkpoint.* points fire right
        # after a journal record is durable, the defrag.*/gang2pc.*
        # points right after each protocol phase — so the deterministic
        # scheduler treats each one as a yield point and can interleave
        # OTHER threads exactly at the boundaries the chaos suites kill
        # at. Read unlocked on the fast path (one attribute load; None
        # in production).
        self._yield_hook: Callable[[str], Any] | None = None

    def set_crash_hook(self, hook: Callable[[str], Any] | None) -> None:
        with self._lock:
            self._crash_hook = hook

    def set_yield_hook(self, hook: Callable[[str], Any] | None) -> None:
        """Install (or clear) the model checker's yield hook, called with
        the point name at the TOP of every :meth:`fire` — before the
        armed-fault check, so an unarmed point still yields."""
        self._yield_hook = hook

    def inject(
        self,
        point: str,
        mode: str = "error",
        *,
        times: int | None = None,
        error: Callable[[], Exception] | None = None,
        latency_s: float = 0.0,
        fail_n: int = 1,
        pass_n: int = 1,
    ) -> None:
        fault = _Fault(
            point, mode, times=times, error=error,
            latency_s=latency_s, fail_n=fail_n, pass_n=pass_n,
        )
        with self._lock:
            self._faults[point] = fault
        log.info("fault armed: %s mode=%s times=%s", point, mode, times)

    def clear(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._faults.clear()
            else:
                self._faults.pop(point, None)

    def active(self) -> list[str]:
        with self._lock:
            return sorted(self._faults)

    def fired(self, point: str) -> int:
        with self._lock:
            f = self._faults.get(point)
            return f.fired if f is not None else 0

    def fire(self, point: str) -> None:
        """Called at the injection site. No-op unless the point is armed
        (and, under the model checker, a scheduler yield point)."""
        hook = self._yield_hook
        if hook is not None:
            hook(point)
        if not self._faults:  # fast path: nothing armed anywhere
            return
        crash: SimulatedCrash | None = None
        with self._lock:
            fault = self._faults.get(point)
            if fault is None:
                return
            # counters/cycle mutate under the lock; the latency sleep and
            # the crash hook's dump I/O must not hold it (they would
            # serialize unrelated points)
            if fault.mode == "latency":
                if fault.times is not None and fault.fired >= fault.times:
                    return
                fault.fired += 1
                delay = fault.latency_s
            else:
                try:
                    fault.apply()  # raises or passes through
                    return
                except SimulatedCrash as e:
                    crash = e
            hook = self._crash_hook
        if crash is not None:
            if hook is not None:
                try:
                    hook(point)
                except Exception as e:  # noqa: BLE001 — crashing anyway
                    log.warning("crash hook failed at %s: %s", point, e)
            raise crash
        time.sleep(delay)

    @contextlib.contextmanager
    def injected(
        self, point: str, mode: str = "error", **kwargs: Any
    ) -> Iterator["FaultRegistry"]:
        """Scoped arming for tests: disarms the point on exit even when the
        body raises."""
        self.inject(point, mode, **kwargs)
        try:
            yield self
        finally:
            self.clear(point)

    def install_from_env(self, spec: str | None = None) -> int:
        """Arm faults from ``TPUSHARE_FAULTS`` (or an explicit spec string).
        Returns the number of faults armed; malformed clauses are logged
        and skipped (a typo in an e2e env must not crash the daemon)."""
        if spec is None:
            spec = os.environ.get(ENV_FAULTS, "")
        armed = 0
        for clause in spec.split(","):
            clause = clause.strip()
            if not clause:
                continue
            try:
                point, _, rhs = clause.partition("=")
                point = point.strip()
                if point not in POINTS:
                    # a typo'd point would arm silently and never fire —
                    # the e2e would then "pass" without injecting anything
                    log.warning(
                        "ignoring unknown fault point %r (known: %s)",
                        point, ", ".join(POINTS),
                    )
                    continue
                mode, _, arg = rhs.partition(":")
                kwargs: dict = {}
                if mode == "latency":
                    kwargs["latency_s"] = float(arg or 0.1)
                elif mode == "flap":
                    fail_s, _, pass_s = (arg or "1/1").partition("/")
                    kwargs["fail_n"] = int(fail_s or 1)
                    kwargs["pass_n"] = int(pass_s or 1)
                elif arg:
                    kwargs["times"] = int(arg)
                self.inject(point, mode or "error", **kwargs)
                armed += 1
            except (ValueError, TypeError) as e:
                log.warning("ignoring malformed fault clause %r: %s", clause, e)
        return armed


# Process-wide registry, mirroring utils.metrics.REGISTRY.
FAULTS = FaultRegistry()
