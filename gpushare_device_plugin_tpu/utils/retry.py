"""Bounded-retry helper with exponential backoff, full jitter, deadlines.

The reference hand-rolls retry loops with fixed budgets (kubelet ``/pods``:
8 x 100ms, ``podmanager.go:143-147``; apiserver list: 3 x 1s,
``podmanager.go:164-169``; inspect CLI: 5 x 100ms). Centralised here so each
call site states its budget declaratively. Fixed-delay retries against a
struggling apiserver synchronize every client into request storms exactly
when the server can least absorb them, so the cluster call sites layer on:

- exponential backoff (``backoff`` multiplier per attempt, capped at
  ``max_delay_s``),
- full jitter (sleep ``uniform(0, current_delay)`` — the AWS
  architecture-blog result: full jitter beats equal/decorrelated jitter
  for contended retries),
- a per-call ``deadline_s`` so a caller with an SLA (the Allocate path
  under kubelet's admission timeout) gets an error while the answer still
  matters, instead of a success that arrives after the caller gave up.

``Backoff`` is the loop-shaped sibling for supervised threads (informer
relist, health-watcher restart): jittered exponential delays with
``reset()`` on success.
"""

from __future__ import annotations

import random
import time
from typing import Callable, TypeVar

T = TypeVar("T")


class RetryError(RuntimeError):
    def __init__(self, attempts: int, last: Exception, deadline: bool = False) -> None:
        why = "deadline exceeded after" if deadline else "all"
        super().__init__(f"{why} {attempts} attempts failed: {last}")
        self.attempts = attempts
        self.last = last
        self.deadline_exceeded = deadline


class Backoff:
    """Full-jitter exponential delays for supervised loops.

    ``next()`` returns ``uniform(0, min(max_s, base_s * factor**n))`` and
    advances; ``reset()`` on success snaps back to the base so a recovered
    dependency is re-engaged promptly.
    """

    def __init__(
        self,
        base_s: float = 0.1,
        max_s: float = 5.0,
        factor: float = 2.0,
        rng: random.Random | None = None,
    ) -> None:
        self._base = base_s
        self._max = max_s
        self._factor = factor
        self._rng = rng or random.Random()
        self._n = 0

    def next(self) -> float:
        # exponent clamped: an hours-long outage must not walk the power
        # into float overflow and kill the supervised loop it paces
        cap = min(self._max, self._base * (self._factor ** min(self._n, 63)))
        self._n += 1
        return self._rng.uniform(0, cap)

    def reset(self) -> None:
        self._n = 0


def retry(
    fn: Callable[[], T],
    *,
    attempts: int,
    delay_s: float,
    backoff: float = 1.0,
    max_delay_s: float | None = None,
    jitter: bool = False,
    deadline_s: float | None = None,
    retryable: Callable[[Exception], bool] = lambda e: True,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    clock: Callable[[], float] = time.monotonic,
) -> T:
    """Call ``fn`` up to ``attempts`` times.

    Defaults preserve the fixed-delay behavior (``delay_s`` between
    tries). ``backoff > 1`` multiplies the delay per attempt, capped at
    ``max_delay_s``; ``jitter=True`` sleeps ``uniform(0, delay)`` instead
    of the full delay; ``deadline_s`` bounds total wall clock — when the
    budget is spent (or the next sleep would overrun it), the last error
    is raised as a ``RetryError`` with ``deadline_exceeded=True``.

    Only ``Exception`` is caught — KeyboardInterrupt/SystemExit propagate so
    signal handling in the daemon stays intact.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    rng = rng or random.Random()
    start = clock()
    delay = delay_s
    last: Exception | None = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - wrapped in RetryError below
            last = e
            if not retryable(e) or i == attempts - 1:
                break
            pause = rng.uniform(0, delay) if jitter else delay
            if deadline_s is not None and (
                clock() - start + pause >= deadline_s
            ):
                raise RetryError(i + 1, last, deadline=True) from last
            sleep(pause)
            delay *= backoff
            if max_delay_s is not None:
                delay = min(delay, max_delay_s)
    assert last is not None
    raise RetryError(attempts, last) from last
