"""Bounded-retry helper.

The reference hand-rolls retry loops with fixed budgets (kubelet ``/pods``:
8 x 100ms, ``podmanager.go:143-147``; apiserver list: 3 x 1s,
``podmanager.go:164-169``; inspect CLI: 5 x 100ms). Centralised here so each
call site states its budget declaratively.
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

T = TypeVar("T")


class RetryError(RuntimeError):
    def __init__(self, attempts: int, last: Exception):
        super().__init__(f"all {attempts} attempts failed: {last}")
        self.attempts = attempts
        self.last = last


def retry(
    fn: Callable[[], T],
    *,
    attempts: int,
    delay_s: float,
    retryable: Callable[[Exception], bool] = lambda e: True,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn`` up to ``attempts`` times, sleeping ``delay_s`` between tries.

    Only ``Exception`` is caught — KeyboardInterrupt/SystemExit propagate so
    signal handling in the daemon stays intact.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    last: Exception | None = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - wrapped in RetryError below
            last = e
            if not retryable(e) or i == attempts - 1:
                break
            sleep(delay_s)
    assert last is not None
    raise RetryError(attempts, last) from last
