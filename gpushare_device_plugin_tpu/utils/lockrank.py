"""Declared lock ranking, ranked-lock factory, and the runtime lock-order
witness for the concurrent control plane.

Why this exists: after the hot path was sharded (PR 2), journaled (PRs
3-4), and taught gang claims (PR 6), sixteen modules hold locks and
several hold more than one at a time. The reference repo leans on
``go test -race``; our Python stand-in was a probabilistic stress loop.
This module makes the locking discipline *declared* instead of implied:

- **The ranking** (:data:`RANKS`): a total order over every lock in the
  package. A thread may only acquire a lock whose rank is strictly
  greater than every lock it already holds (re-entering the same RLock
  is fine). Any two code paths that respect the ranking can never
  deadlock, because a wait-for cycle needs at least one edge that goes
  down-rank. ``docs/analysis.md`` documents the order and the reasoning
  behind each level.
- **The factory** (:func:`make_lock` / :func:`make_rlock` /
  :func:`make_condition`): every lock in the package is created through
  it, naming its rank. Production gets plain ``threading`` primitives;
  under the witness (see below) it returns instrumented wrappers. The
  name doubles as ground truth for the static analyzer
  (``tools/tpulint``), which maps ``self._lock = make_lock("x")``
  declarations to ranks and checks every ``with``-nesting and
  cross-module call chain against the same table.
- **The witness**: with ``TPUSHARE_LOCK_WITNESS=1`` or
  ``TPUSHARE_TEST_CHAOS=1`` in the environment (or
  :func:`set_witness` ``(True)``, which the test suite uses), acquires
  are checked against the ranking per thread at runtime, and the
  acquisition stack of every held lock is recorded so a violation
  report shows *both* sides of the inversion. Violations are recorded
  (and optionally raised, ``TPUSHARE_LOCK_WITNESS_RAISE=1``); the test
  harness fails any test that produced one. This turns the stress suite
  from a dice roll (an inversion only fails if the interleaving
  actually deadlocks) into a deterministic detector (an inversion fails
  the moment either side of the bad ordering *runs*, on any schedule).

- **The model-checker seam** (:func:`set_mc_factory`): the factory is
  ALSO the instrumentation point for ``tools/tpumc``, the bounded
  model checker for the journaled protocols. Under exploration
  (``TPUSHARE_MC=1``, installed programmatically by the tpumc driver)
  every ``make_lock``/``make_rlock``/``make_condition``/``make_event``
  call returns a cooperative primitive whose acquire/release/wait/set
  is a deterministic-scheduler yield point, so thread interleavings
  become enumerable instead of whatever the OS happens to pick. The
  factory still rank-validates first — the checker explores only lock
  graphs the ranking admits.

This module must stay import-light (stdlib only, no package imports):
everything else in the package imports it to create locks.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
from typing import Any, Iterator


@dataclasses.dataclass(frozen=True)
class LockRank:
    """One declared lock level.

    ``rank``: the total order — acquire strictly upward only.
    ``kind``: "lock" | "rlock" | "condition" (what the factory returns;
    the static analyzer uses it to allow same-lock re-entry for rlocks).
    ``io_ok``: whether blocking I/O (network round-trips, fsync waits)
    is permitted while the lock is held. The static analyzer enforces
    this; the runtime witness only checks ordering.
    """

    name: str
    rank: int
    kind: str
    io_ok: bool
    doc: str


def _r(name: str, rank: int, kind: str, io_ok: bool, doc: str) -> tuple[str, LockRank]:
    return name, LockRank(name, rank, kind, io_ok, doc)


# The declared ranking. Lower rank = acquired earlier (outermost).
# docs/analysis.md carries the prose version; keep the two in sync.
RANKS: dict[str, LockRank] = dict(
    (
        _r(
            "allocator.serial", 10, "rlock", True,
            "Legacy full-serialization guard for list-backed pod sources "
            "(AssumeCache.serial_lock): wraps an entire admission, PATCH "
            "included, so it outranks everything and is the one lock "
            "allowed to cover the full I/O flow.",
        ),
        _r(
            "extender.lease", 16, "lock", False,
            "LeaderLease's per-gang-group coordinator epochs (the 2PC "
            "fencing tokens). Pure memory, acquired before any shard "
            "verb runs — outermost of the shard-layer locks.",
        ),
        _r(
            "extender.router", 17, "lock", False,
            "ShardRouter's cached shard summaries + degraded-shard "
            "bookkeeping. Never held across a shard verb call (those "
            "acquire extender.core and the ledger further down-rank).",
        ),
        _r(
            "extender.simchurn", 19, "lock", False,
            "ChurnDriver's stats/death-heap guard (the scale bench's "
            "simulated-cluster worker pool). Held around counter and "
            "heap flips only — admissions, apiserver calls, and shard "
            "verbs all run with it released.",
        ),
        _r(
            "extender.core", 20, "rlock", False,
            "ExtenderCore's decision lock: guards the in-flight overlay "
            "and the view cache while a bind decision is made. In-memory "
            "only by design — a network or fsync wait here serializes "
            "every bind in the cluster behind one I/O.",
        ),
        _r(
            "extender.twopc", 21, "lock", False,
            "ShardExtender's 2PC side-state (gang2pc reservation key -> "
            "node map, seen coordinator epochs). Read by the shard's "
            "usage-overlay hook while the core's decision lock (rank 20) "
            "is held, so it sits just above extender.core; the journal "
            "write and the ledger reserve run outside it, strictly "
            "up-rank.",
        ),
        _r(
            "allocator.match", 22, "lock", True,
            "Per-size match stripes (ClusterAllocator/_CoreAllocator): "
            "serialize same-size matches. May refresh() the pod source "
            "(one synchronous LIST) on a match miss — the documented "
            "close-the-bind-window exception, so I/O is allowed.",
        ),
        _r(
            "defrag.planner", 24, "lock", False,
            "DefragPlanner's cached last-scan report: the defrag loop "
            "writes it, the CLI/status publisher reads it. In-memory "
            "only; the scan's pod reads run before the lock is taken.",
        ),
        _r(
            "defrag.moves", 26, "lock", False,
            "SliceMover's move-state counters (planned/active/completed/"
            "last duration). Never held across a journal fsync or the "
            "switch PATCH — the move protocol's I/O runs between, not "
            "under, counter updates.",
        ),
        _r(
            "allocator.ledger", 30, "rlock", False,
            "AssumeCache's claim/reservation ledger: one atomic "
            "snapshot-overlay-decide-reserve step. Pure memory; the "
            "lock-wait histogram exists to catch I/O creeping back in.",
        ),
        _r(
            "checkpoint.journal", 40, "rlock", True,
            "AllocationCheckpoint's entry/sequence state. In `always` "
            "mode the record append+fsync runs under it by design "
            "(durability before the caller proceeds), so I/O is allowed.",
        ),
        _r(
            "informer.cache", 50, "lock", False,
            "PodInformer's cache/tombstone map and index fan-out. Watch "
            "apply, merge, and reads are in-memory; the LIST that feeds "
            "refresh()/relist runs before the lock is taken.",
        ),
        _r(
            "cluster.usage", 60, "lock", False,
            "NodeChipUsage per-chip aggregates (maintained under "
            "informer.cache via the index protocol).",
        ),
        _r(
            "cluster.podindex", 61, "lock", False,
            "Bucketed pod-set indexes (pending-by-resource, "
            "labeled-by-value); same nesting as cluster.usage.",
        ),
        _r(
            "extender.usageindex", 62, "lock", False,
            "ClusterUsageIndex per-node aggregates + generations; "
            "maintained under informer.cache, read under extender.core.",
        ),
        _r(
            "cluster.interference", 63, "lock", False,
            "InterferenceDetector's baseline/report state: per-victim "
            "solo-window step-p99 baselines and the last pass's verdicts. "
            "Inputs (chip residency, step p99s) are gathered BEFORE the "
            "lock is taken; gauges publish after it is dropped.",
        ),
        _r(
            "slo.budget", 64, "lock", False,
            "SloBudget's time-bucketed good/bad event counters and "
            "burn-rate state. record() runs at engine retire (no other "
            "lock held); evaluate() snapshots under it and fires the "
            "page hook (flight-recorder dump) outside.",
        ),
        _r(
            "decisions.ring", 65, "lock", False,
            "DecisionLog's bounded ring of admission decision records: "
            "verbs append AFTER their locked decision sections (no other "
            "lock held), the /decisions endpoint snapshots under it and "
            "serializes outside. Pure memory — the segment write runs "
            "under decisions.segment, never here.",
        ),
        _r(
            "decisions.segment", 66, "lock", True,
            "DecisionLog's on-disk segment appender: one JSON line per "
            "record, flushed to the OS buffer but never fsynced "
            "(provenance is observability, not durability — the WAL owns "
            "that). I/O by definition; taken only after decisions.ring "
            "is released.",
        ),
        _r(
            "timeline.ring", 67, "lock", False,
            "ClusterTimeline's time-bucketed sample ring: the sampler "
            "loop writes one bucket per tick, /timeline and the flight "
            "recorder snapshot under it and serialize outside. Pure "
            "memory, fixed-size by construction.",
        ),
        _r(
            "wal.batcher", 70, "condition", False,
            "GroupBatcher's queue condition: submit() runs under "
            "checkpoint.journal; the flush itself happens with the "
            "condition released (the worker drains, then writes).",
        ),
        _r(
            "checkpoint.io", 75, "lock", True,
            "The journal's file-handle discipline: open/write/fsync/"
            "rename. Never held while waiting for checkpoint.journal "
            "(that ordering is the point of the two-lock split).",
        ),
        _r(
            "fleet.router", 76, "lock", False,
            "FleetRouter's routing table (rid -> engine assignment, "
            "round-robin cursor, per-path counters). Pure memory: SLO "
            "severity reads (slo.budget, rank 64) and decision-record "
            "emission (decisions.ring, rank 65) both run BEFORE the lock "
            "is taken / after it is dropped — they sit down-rank by "
            "design. Membership snapshots (fleet.membership, rank 77) "
            "nest strictly up-rank.",
        ),
        _r(
            "fleet.membership", 77, "lock", False,
            "FleetMembership's replica table (health, consecutive "
            "scrape misses, cordon flags, prefix fingerprints, load "
            "estimates). Held around table flips only — never across a "
            "scrape transport call or its circuit breaker (rank 88); "
            "replica-state gauges publish (metrics.registry, rank 95) "
            "under it, strictly up-rank.",
        ),
        _r(
            "fleet.scale", 78, "lock", False,
            "ScaleExecutor's in-flight scale-op state (scale_id -> "
            "phase, migrated-request counters). Counter/state flips "
            "only: the journal write (checkpoint.journal, rank 40) and "
            "the engine drain handshake (serving.drain, rank 89) both "
            "run with this lock released — the protocol's I/O and "
            "engine calls are never under it, mirroring defrag.moves.",
        ),
        _r(
            "serving.adapters", 79, "lock", False,
            "AdapterCache's residency table (adapter id -> slab pages, "
            "pin counts, LRU clock, hit/miss/eviction/stall telemetry). "
            "Loads and evictions allocate/release through the page "
            "allocator (serving.pages, rank 87) while held — strictly "
            "up-rank, the serving.handoff precedent. Device slab writes "
            "happen in the engine loop with this lock released.",
        ),
        _r(
            "apiserver.coalescer", 80, "lock", False,
            "Lazy construction of the node-PATCH coalescer; the merged "
            "PATCH itself runs outside it.",
        ),
        _r(
            "handoff.peer", 81, "lock", False,
            "HandoffPeerClient's transfer counters (calls, retries, "
            "pages/bytes shipped). Never held across a transport call "
            "or the circuit breaker (rank 88) — counter flips only.",
        ),
        _r(
            "plugin.stream", 82, "condition", False,
            "TpuSharePlugin's ListAndWatch/drain condition: health map, "
            "version counter, in-flight Allocate count. Allocate "
            "releases it before delegating to the allocator.",
        ),
        _r(
            "serving.handoff", 83, "lock", False,
            "HandoffImportLedger's staging table (destination pages "
            "reserved per in-flight KV handoff, received page bytes, "
            "delivered-id dedup window). Staging allocates through the "
            "page allocator (serving.pages, rank 87) while held — "
            "strictly up-rank.",
        ),
        _r(
            "manager.health", 84, "lock", False,
            "HealthWatcher's unhealthy-chip set.",
        ),
        _r(
            "serving.radix", 85, "lock", False,
            "RadixCache's shared-prefix tree (nodes, LRU clock, hit "
            "telemetry). Page reference updates (serving.pages, rank "
            "87) run after the tree lock is dropped; any unavoidable "
            "nesting goes radix -> pages, strictly up-rank.",
        ),
        _r(
            "allocator.local", 86, "lock", False,
            "LocalAllocator's standalone usage table (never nests over "
            "cluster locks; ranked near the leaves).",
        ),
        _r(
            "serving.pages", 87, "lock", False,
            "PageAllocator's free list + refcounts: the serving "
            "engine's host loop and the /metrics scrape thread both "
            "read occupancy. Pure memory, near-leaf; publish() snapshots "
            "under it and writes gauges (metrics.registry, rank 95) "
            "outside.",
        ),
        _r(
            "circuit.breaker", 88, "lock", False,
            "CircuitBreaker state counters; the guarded call runs with "
            "the lock released.",
        ),
        _r(
            "serving.drain", 89, "lock", False,
            "PagedSlotEngine's drain-handshake state (arm / capture / "
            "consume transitions of the _drain/_drained events and the "
            "captured snapshot). Near-leaf: held around Event/dict "
            "flips a few times per run — never per tick, never over "
            "another lock.",
        ),
        _r(
            "faults.registry", 90, "lock", False,
            "Fault-injection rule table; fire() sites run everywhere, "
            "so this must be a near-leaf.",
        ),
        _r(
            "serving.profiler", 91, "lock", False,
            "StepProfiler's preallocated per-decode-step ring + "
            "counters: the engine's host loop writes one float per "
            "decode dispatch, the /metrics publisher and the "
            "interference detector read rolling quantiles. Near-leaf "
            "pure memory; flush() snapshots under it and feeds the "
            "metrics registry (rank 95) outside.",
        ),
        _r(
            "tracing.admissions", 92, "lock", False,
            "AdmissionTraces' per-pod root-span registry: correlates the "
            "extender's separate webhook verbs into one trace. Ends "
            "spans (which append to tracing.store, rank 93) under it, "
            "so it sits just below the store.",
        ),
        _r(
            "tracing.store", 93, "lock", False,
            "TraceStore's finished-span ring: spans end under almost "
            "any other lock (a traced section can close inside a locked "
            "region), so the store is a near-leaf like the metrics "
            "registry. Pure memory — export snapshots, then serializes "
            "outside the lock.",
        ),
        _r(
            "flightrec.ring", 94, "lock", False,
            "FlightRecorder's bounded log-record ring: fed from a "
            "logging handler, which can run under any lock that logs. "
            "dump() snapshots under it and writes the file outside.",
        ),
        _r(
            "metrics.registry", 95, "lock", False,
            "MetricsRegistry: the innermost leaf — counters and "
            "histograms are recorded under every other lock.",
        ),
    )
)


def rank_of(name: str) -> LockRank:
    try:
        return RANKS[name]
    except KeyError:
        raise ValueError(
            f"unknown lock rank {name!r}; declare it in "
            "gpushare_device_plugin_tpu/utils/lockrank.py RANKS "
            "(and docs/analysis.md)"
        ) from None


class LockOrderError(RuntimeError):
    """A thread acquired locks against the declared ranking."""


@dataclasses.dataclass(frozen=True)
class Violation:
    """One observed ordering violation: ``acquiring`` was requested while
    ``holding`` (same or higher rank, different lock) was held."""

    thread: str
    acquiring: str
    acquiring_rank: int
    holding: str
    holding_rank: int
    acquire_stack: str
    held_stack: str

    def brief(self) -> str:
        return (
            f"[{self.thread}] acquiring {self.acquiring!r} "
            f"(rank {self.acquiring_rank}) while holding {self.holding!r} "
            f"(rank {self.holding_rank})"
        )

    def report(self) -> str:
        return (
            f"{self.brief()}\n"
            f"--- held lock acquired at ---\n{self.held_stack}"
            f"--- violating acquire at ---\n{self.acquire_stack}"
        )


# Witness state. The guard is a RAW threading.Lock on purpose: the witness
# must never recurse into itself.
_state_lock = threading.Lock()
_violations: list[Violation] = []
_forced: bool | None = None  # set_witness() override; None = env decides

_TRUTHY = ("1", "true", "yes", "on")


def _env_enabled() -> bool:
    return (
        os.environ.get("TPUSHARE_LOCK_WITNESS", "").lower() in _TRUTHY
        or os.environ.get("TPUSHARE_TEST_CHAOS", "").lower() in _TRUTHY
    )


def witness_enabled() -> bool:
    """Whether locks created *now* are witnessed."""
    if _forced is not None:
        return _forced
    return _env_enabled()


def set_witness(enabled: bool | None) -> None:
    """Force the witness on/off for locks created from now on (None =
    defer to the environment again). The witness suites use this per
    test; plain tier-1 runs with the witness OFF (a few perf-ratio tests
    measure real lock costs) — `make chaos` / `make test-stress` enable
    it via the environment, and the conftest fixture fails whichever
    test recorded an inversion."""
    global _forced
    _forced = enabled


def violations() -> list[Violation]:
    with _state_lock:
        return list(_violations)


def reset_violations() -> None:
    with _state_lock:
        _violations.clear()


def assert_clean(context: str = "") -> None:
    """Hard gate for benches and suites: raise listing every recorded
    inversion. The stress/chaos/storm drivers call this so an observed
    inversion fails the run deterministically."""
    found = violations()
    if found:
        where = f" during {context}" if context else ""
        raise LockOrderError(
            f"{len(found)} lock-order violation(s) observed{where}:\n"
            + "\n".join(v.report() for v in found)
        )


def _record(violation: Violation) -> None:
    with _state_lock:
        _violations.append(violation)
    if os.environ.get("TPUSHARE_LOCK_WITNESS_RAISE", "").lower() in _TRUTHY:
        raise LockOrderError(violation.report())


class _HeldStack(threading.local):
    def __init__(self) -> None:
        # [(lock id, name, rank, count, acquisition stack)]
        self.entries: list[list[Any]] = []


_held = _HeldStack()

# Cross-thread Lock handoff support (A acquires, B releases — legal for
# plain Locks): id(lock) -> the acquiring thread's entries list + entry,
# so B's release can remove A's bookkeeping instead of leaking it into
# false violations for the rest of A's life. Guarded by _state_lock;
# non-reentrant locks only (RLock forbids cross-thread release anyway).
_handoff: dict[int, tuple[list[list[Any]], list[Any]]] = {}


def held_locks() -> list[tuple[str, int]]:
    """(name, count) for every witnessed lock this thread holds —
    introspection for tests and violation reports."""
    return [(e[1], e[3]) for e in _held.entries]


def _stack() -> str:
    # Cheap frame walk (no source-line reads — this runs on EVERY witnessed
    # acquire): file:line per frame, witness frames dropped, outermost first.
    frames = []
    f = sys._getframe(2)
    for _ in range(10):
        if f is None:
            break
        frames.append(f"  {f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    return "\n".join(reversed(frames)) + "\n"


class _WitnessedLock:
    """Order-checking wrapper over a threading Lock/RLock.

    Exposes the primitive protocol (``acquire``/``release``/context
    manager) plus the pieces ``threading.Condition`` probes for
    (``_is_owned``, ``_release_save``/``_acquire_restore`` when the
    inner lock provides them), so a Condition built over a witnessed
    RLock behaves exactly like one over a bare RLock — including
    ``wait()``'s release/re-acquire, which the witness tracks."""

    __slots__ = ("_name", "_rank", "_inner", "_reentrant")

    def __init__(self, name: str, inner: Any, reentrant: bool) -> None:
        self._name = name
        self._rank = RANKS[name].rank
        self._inner = inner
        self._reentrant = reentrant

    # --- witness bookkeeping ---------------------------------------------

    def _entry(self) -> list[Any] | None:
        me = id(self)
        for e in _held.entries:
            if e[0] == me:
                return e
        return None

    def _check_order(self) -> None:
        mine = self._entry()
        if mine is not None:
            if self._reentrant:
                return  # RLock re-entry: always legal
            # Re-acquiring a held non-reentrant lock is a GUARANTEED
            # self-deadlock — record it and raise instead of hanging the
            # suite with zero diagnostics (there is no false-positive
            # risk: proceeding would block this thread forever).
            violation = Violation(
                thread=threading.current_thread().name,
                acquiring=self._name,
                acquiring_rank=self._rank,
                holding=self._name,
                holding_rank=self._rank,
                acquire_stack=_stack(),
                held_stack=mine[4],
            )
            _record(violation)
            raise LockOrderError(
                "self-deadlock: non-reentrant lock re-acquired by its "
                "holder\n" + violation.report()
            )
        # mine is None here: every self-held case returned or raised above
        for e in _held.entries:
            if e[2] >= self._rank:
                _record(
                    Violation(
                        thread=threading.current_thread().name,
                        acquiring=self._name,
                        acquiring_rank=self._rank,
                        holding=e[1],
                        holding_rank=e[2],
                        acquire_stack=_stack(),
                        held_stack=e[4],
                    )
                )

    def _push(self, n: int = 1) -> None:
        mine = self._entry()
        if mine is not None and self._reentrant:
            mine[3] += n
            return
        entry = [id(self), self._name, self._rank, n, _stack()]
        _held.entries.append(entry)
        if not self._reentrant:
            with _state_lock:
                _handoff[id(self)] = (_held.entries, entry)

    def _pop(self, n: int = 1) -> None:
        mine = self._entry()
        if mine is None:
            # released by a thread that never acquired (Lock handoff):
            # remove the acquiring thread's entry so its witness stack
            # does not leak into false violations
            with _state_lock:
                owner = _handoff.pop(id(self), None)
            if owner is not None:
                entries, entry = owner
                if entry in entries:
                    entries.remove(entry)
            return
        mine[3] -= n
        if mine[3] <= 0:
            _held.entries.remove(mine)
            if not self._reentrant:
                with _state_lock:
                    _handoff.pop(id(self), None)

    # --- lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._push()
        return got

    def release(self) -> None:
        self._inner.release()
        self._pop()

    def __enter__(self) -> "_WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __getattr__(self, name: str) -> Any:
        # Delegate anything else (e.g. Lock.locked, absent on RLock before
        # py3.14) so the wrapper exposes exactly the inner primitive's
        # surface — no more, no less.
        if name == "_inner":  # unset slot (mid-copy): no recursion
            raise AttributeError(name)
        return getattr(self._inner, name)

    # --- Condition support ------------------------------------------------

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self) -> Any:
        # Condition.wait() on an RLock: release the full recursion depth.
        state = self._inner._release_save()
        mine = self._entry()
        depth = mine[3] if mine is not None else 1
        self._pop(depth)
        return (state, depth)

    def _acquire_restore(self, saved: Any) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        self._push(depth)

    def __repr__(self) -> str:
        return f"<WitnessedLock {self._name} over {self._inner!r}>"


# --- model-checker factory seam ---------------------------------------------

# When installed (tools/tpumc), the factory functions below delegate
# primitive construction here AFTER rank validation: the checker's
# cooperative primitives replace threading's, and every acquire/release/
# wait/set becomes a deterministic-scheduler yield point. None in
# production — one module-global read on the construction path, nothing
# on the acquire path.
_mc_factory: Any | None = None


def set_mc_factory(factory: Any | None) -> None:
    """Install (or clear, with None) the model checker's primitive
    factory. The object must expose ``lock(name)``, ``rlock(name)``,
    ``condition(name)``, and ``event(name)``. Affects primitives created
    from now on — the tpumc driver installs it before building a model's
    harness, so every lock in the harness's object graph is cooperative,
    while import-time singletons (metrics registry, fault table, trace
    store) stay plain and therefore atomic to the explorer: near-leaf
    telemetry chatter is not worth schedule-space."""
    global _mc_factory
    _mc_factory = factory


def mc_active() -> bool:
    """Whether primitives created now are model-checker cooperative."""
    return _mc_factory is not None


def make_lock(name: str) -> Any:
    """A non-reentrant mutex at the declared rank ``name``. The declared
    kind must match: handing out a plain Lock for a rank the static
    analyzer treats as reentrant would bless re-entries that self-deadlock
    in production (witness off)."""
    rank = rank_of(name)
    if rank.kind != "lock":
        raise ValueError(
            f"{name} is declared {rank.kind}; use make_{rank.kind}"
        )
    if _mc_factory is not None:
        return _mc_factory.lock(name)
    if witness_enabled():
        return _WitnessedLock(name, threading.Lock(), reentrant=False)
    return threading.Lock()


def make_rlock(name: str) -> Any:
    """A reentrant mutex at the declared rank ``name`` (kind-checked, see
    :func:`make_lock`)."""
    rank = rank_of(name)
    if rank.kind != "rlock":
        raise ValueError(
            f"{name} is declared {rank.kind}; use make_{rank.kind}"
        )
    if _mc_factory is not None:
        return _mc_factory.rlock(name)
    if witness_enabled():
        return _WitnessedLock(name, threading.RLock(), reentrant=True)
    return threading.RLock()


def make_condition(name: str) -> threading.Condition:
    """A condition variable whose underlying mutex carries rank ``name``
    (``wait()`` releases and re-acquires through the witness)."""
    rank = rank_of(name)
    if rank.kind != "condition":
        raise ValueError(
            f"{name} is declared {rank.kind}; use make_{rank.kind}"
        )
    if _mc_factory is not None:
        return _mc_factory.condition(name)
    if witness_enabled():
        return threading.Condition(
            _WitnessedLock(name, threading.RLock(), reentrant=True)
        )
    return threading.Condition()


def make_event(name: str) -> Any:
    """An event flag named for diagnostics. Events carry NO rank — they
    are not mutual exclusion and impose no acquisition ordering, so the
    witness has nothing to check — but they ARE scheduling: a ``wait``
    parks a thread and a ``set`` releases it, which is exactly what the
    model checker must control. The factory exists so protocol state
    machines built on events (the serving engine's drain handshake)
    construct them through the same seam as their locks and become fully
    explorable under ``tools/tpumc``."""
    if _mc_factory is not None:
        return _mc_factory.event(name)
    return threading.Event()


def ordered(names: list[str]) -> Iterator[LockRank]:
    """The declared ranks for ``names``, sorted outermost-first (docs and
    report tooling)."""
    return iter(sorted((rank_of(n) for n in names), key=lambda r: r.rank))
