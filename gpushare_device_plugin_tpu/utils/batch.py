"""Group-commit batching primitive shared by the WAL writer and the
apiserver PATCH coalescers.

The pattern (databases call it group commit): many callers each need one
expensive flush-like operation — an ``fsync``, an HTTP PATCH round-trip —
and the operation's cost is dominated by fixed overhead, not payload. A
dedicated worker drains everything submitted since the last flush and pays
the overhead ONCE for the whole batch; each caller blocks on a per-batch
ticket until *its* item has been processed, so the blocking semantics are
exactly those of doing the work inline — only the per-call overhead is
amortized.

Gather dynamics: the worker wakes on the first submission and gathers up
to ``window_s`` before flushing — but only while the batcher is *busy*
(another flush ran within the last few windows). From idle, a lone
submission drains as soon as arrivals go quiet for ``window_s / 4``: a
sporadic sequential caller pays ~window/4 of added latency, while a
16-way admission storm — where arrivals keep coming but may be smeared
by CPU scheduling — gets the full window and batches deeply. The flush
duration itself is a second, free batching window: submissions during a
flush queue up for the next one.

Failure semantics: ``flush_fn`` may return per-item results (an
``Exception`` instance fails just that ticket) or raise to fail the whole
batch. A ``BaseException`` (``SimulatedCrash`` from the fault layer) is
propagated to every waiting ticket AND re-raised in the worker — exactly
like a process dying mid-flush; the worker is restarted lazily on the
next submit, so a ``times=1`` injected crash doesn't wedge the batcher
for the rest of the process lifetime.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

from .lockrank import make_condition
from .log import get_logger

log = get_logger("utils.batch")


class Ticket:
    """One submitted item's handle: ``wait()`` blocks until the batch that
    carried the item was flushed, then returns its per-item result or
    raises its per-item (or whole-batch) error."""

    __slots__ = ("_done", "_result", "_error")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result: Any = None
        self._error: BaseException | None = None

    def _resolve(self, result: Any = None) -> None:
        self._result = result
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()

    def wait(self, timeout: float | None = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError("batched operation did not complete in time")
        if self._error is not None:
            raise self._error
        return self._result


class GroupBatcher:
    """``submit(item) -> Ticket``; a worker thread drains queued items and
    calls ``flush_fn(items)`` once per batch.

    ``flush_fn(items)`` returns either ``None`` (every ticket resolves to
    ``None``) or a sequence of per-item results aligned with ``items``
    (an ``Exception`` element fails that one ticket). ``on_batch``, if
    given, observes ``len(items)`` after each successful flush (metrics
    hook — kept out of flush_fn so failures aren't counted as batches).
    """

    def __init__(
        self,
        flush_fn: Callable[[list], Sequence | None],
        window_s: float = 0.002,
        name: str = "batcher",
        on_batch: Callable[[int], None] | None = None,
        idle_exit_s: float = 30.0,
    ) -> None:
        self._flush_fn = flush_fn
        self._window = max(0.0, window_s)
        self._name = name
        self._on_batch = on_batch
        # A worker with nothing to do for this long exits; the next
        # submit restarts it. Batchers live as long as their owners
        # (clients, checkpoints) and owners are created freely in tests —
        # without the idle exit every one would pin a thread forever.
        self._idle_exit_s = idle_exit_s
        self._cond = make_condition("wal.batcher")
        self._queue: list[tuple[Any, Ticket]] = []
        self._thread: threading.Thread | None = None
        self._stopping = False
        self._killed = False
        self._force = False  # flush(): drain now, skip the gather window
        # barrier bookkeeping: submit seq vs highest seq fully flushed
        self._submitted = 0
        self._completed = 0
        self._last_flush = float("-inf")  # monotonic stamp of last drain

    # --- caller side ------------------------------------------------------

    def submit(self, item: Any) -> Ticket:
        ticket = Ticket()
        with self._cond:
            if self._killed or self._stopping:
                raise RuntimeError(f"{self._name}: batcher is stopped")
            self._queue.append((item, ticket))
            self._submitted += 1
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return ticket

    def flush(self, timeout: float | None = None) -> bool:
        """Barrier: returns once everything submitted before this call has
        been flushed (durable / responded). False on timeout."""
        with self._cond:
            target = self._submitted
            self._force = True
            self._cond.notify_all()
            return self._cond.wait_for(
                lambda: self._completed >= target
                or (self._thread is None or not self._thread.is_alive())
                and not self._queue,
                timeout=timeout,
            )

    def stop(self) -> None:
        """Graceful: flush whatever is queued, then stop the worker."""
        with self._cond:
            self._stopping = True
            self._force = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)

    def kill(self) -> None:
        """Test hook simulating process death: discard the queue without
        flushing (a SIGKILL'd daemon's batched-but-unfsynced records are
        exactly this — gone). Tickets are failed, not left hanging."""
        with self._cond:
            self._killed = True
            self._stopping = True
            dropped = self._queue
            self._queue = []
            for _item, ticket in dropped:
                ticket._fail(RuntimeError(f"{self._name}: killed, batch dropped"))
            self._completed = self._submitted
            self._cond.notify_all()

    # --- worker side ------------------------------------------------------

    def _gather(self) -> list[tuple[Any, Ticket]]:
        """Caller must hold self._cond. Blocks for the first item, then
        applies the window/quiet gather policy; returns the drained batch
        (empty only when stopping with nothing queued)."""
        import time

        idle_deadline = time.monotonic() + self._idle_exit_s
        while not self._queue:
            if self._stopping:
                return []
            remaining = idle_deadline - time.monotonic()
            if remaining <= 0:
                return []  # idle exit: the next submit restarts the worker
            self._cond.wait(remaining)
        if self._window > 0 and not self._force and not self._stopping:
            now = time.monotonic()
            # busy = a flush ran recently: more work is very likely in
            # flight even if arrivals are smeared — hold the full window.
            busy = now - self._last_flush < 4.0 * self._window
            deadline = now + self._window
            quiet = self._window / 4.0
            seen = len(self._queue)
            while not self._force and not self._stopping:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining if busy else min(quiet, remaining))
                if not busy and len(self._queue) == seen:
                    break  # idle-mode: arrivals went quiet, drain early
                seen = len(self._queue)
        self._force = False
        self._last_flush = time.monotonic()
        batch = self._queue
        self._queue = []
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                batch = self._gather()
                if not batch:
                    # Hand the restart duty off BEFORE dying: a submit()
                    # racing the idle exit would otherwise see a
                    # still-is_alive() thread that has already made its
                    # final queue check, enqueue, and hang its ticket
                    # until some unrelated later submit. Clearing _thread
                    # under the lock makes that submit start a fresh
                    # worker.
                    if self._thread is threading.current_thread():
                        self._thread = None
                    self._cond.notify_all()
                    return
            try:
                results = self._flush_fn([item for item, _t in batch])
            except BaseException as e:  # noqa: BLE001 — per-design, see module doc
                for _item, ticket in batch:
                    ticket._fail(e)
                with self._cond:
                    self._completed += len(batch)
                    if not isinstance(e, Exception):
                        # SimulatedCrash: the worker dies like the process
                        # would — and items already queued for the next
                        # batch die with it (their callers must not hang;
                        # a later submit lazily restarts the worker).
                        for _item, ticket in self._queue:
                            ticket._fail(e)
                        self._completed += len(self._queue)
                        self._queue = []
                        if self._thread is threading.current_thread():
                            self._thread = None  # see idle-exit handoff
                        self._cond.notify_all()
                        return
                    self._cond.notify_all()
                continue
            for i, (_item, ticket) in enumerate(batch):
                r = results[i] if results is not None else None
                if isinstance(r, BaseException):
                    ticket._fail(r)
                else:
                    ticket._resolve(r)
            if self._on_batch is not None:
                try:
                    self._on_batch(len(batch))
                except Exception as e:  # noqa: BLE001 — metrics must not kill I/O
                    log.warning("%s: on_batch hook failed: %s", self._name, e)
            with self._cond:
                self._completed += len(batch)
                self._cond.notify_all()
