"""Circuit breaker for the apiserver client.

During a control-plane outage every call pays the full connect timeout
(10 s default). On the Allocate path that stalls kubelet's admission
worker; stacked across the informer relist loop, the event emitter, and
the node patch it turns one outage into a daemon-wide pile-up of blocked
threads. The breaker converts that into fail-fast: after
``failure_threshold`` consecutive transport failures the circuit opens and
callers get ``CircuitOpenError`` immediately (kubelet retries admission;
the informer serves its last-good cache) until a half-open probe after
``reset_timeout_s`` succeeds and closes it again.

Classic three-state machine:

    CLOSED --(N consecutive failures)--> OPEN
    OPEN   --(reset_timeout elapsed)---> HALF_OPEN (one probe admitted)
    HALF_OPEN --success--> CLOSED | --failure--> OPEN

State is exported as ``tpushare_circuit_state`` (0 closed / 1 half-open /
2 open) plus transition and fast-fail counters, so the degraded mode is
visible on the scrape the moment it engages.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from .log import get_logger
from .metric_catalog import (
    CIRCUIT_FASTFAIL_TOTAL,
    CIRCUIT_STATE,
    CIRCUIT_TRANSITIONS_TOTAL,
)
from .metrics import REGISTRY
from .lockrank import make_lock

log = get_logger("utils.circuit")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(RuntimeError):
    """Fail-fast rejection while the circuit is open. Deliberately NOT an
    ``ApiError``: it must not be mistaken for a server-issued status (a
    404-driven evict, a 409 conflict retry) — callers see it as what it
    is, a client-side refusal to dial a known-down endpoint."""

    def __init__(self, name: str, retry_after_s: float) -> None:
        super().__init__(
            f"circuit '{name}' open: apiserver unreachable, "
            f"failing fast (next probe in {retry_after_s:.1f}s)"
        )
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    def __init__(
        self,
        name: str = "apiserver",
        failure_threshold: int = 5,
        reset_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self._threshold = failure_threshold
        self._reset_timeout = reset_timeout_s
        self._clock = clock
        self._lock = make_lock("circuit.breaker")
        self._state = CLOSED
        self._failures = 0  # consecutive
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._export()

    # ------------------------------------------------------------------

    def _export(self) -> None:
        REGISTRY.gauge_set(
            CIRCUIT_STATE,
            _STATE_VALUE[self._state],
            "Breaker state: 0 closed, 1 half-open, 2 open",
            breaker=self.name,
        )

    def _transition(self, state: str) -> None:
        """Caller must hold self._lock."""
        if state == self._state:
            return
        log.warning("circuit '%s': %s -> %s", self.name, self._state, state)
        self._state = state
        REGISTRY.counter_inc(
            CIRCUIT_TRANSITIONS_TOTAL,
            "Breaker state transitions",
            breaker=self.name, to=state,
        )
        self._export()

    @property
    def state(self) -> str:
        with self._lock:
            # surface OPEN->HALF_OPEN eligibility without requiring a call
            if (
                self._state == OPEN
                and self._clock() - self._opened_at >= self._reset_timeout
            ):
                return HALF_OPEN
            return self._state

    # ------------------------------------------------------------------

    def before(self) -> None:
        """Gate one call. Raises ``CircuitOpenError`` when open; admits a
        single probe when the reset window has elapsed."""
        with self._lock:
            if self._state == CLOSED:
                return
            elapsed = self._clock() - self._opened_at
            if self._state == OPEN and elapsed >= self._reset_timeout:
                self._transition(HALF_OPEN)
                self._probe_in_flight = False
            if self._state == HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True  # this caller is the probe
                return
            REGISTRY.counter_inc(
                CIRCUIT_FASTFAIL_TOTAL,
                "Calls rejected while the circuit was open",
                breaker=self.name,
            )
            raise CircuitOpenError(
                self.name, max(0.0, self._reset_timeout - elapsed)
            )

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_in_flight = False
            if self._state == HALF_OPEN or self._failures >= self._threshold:
                self._opened_at = self._clock()
                self._transition(OPEN)

    def call(self, fn: Callable) -> Any:
        """Convenience guard: ``before()`` + outcome accounting around one
        callable (exception = failure, return = success)."""
        self.before()
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result
