"""Leveled logging with glog-style verbosity tiers.

The reference logs through glog with ``--v`` verbosity (V(1) lifecycle,
V(4)/V(6) per-decision detail; DaemonSet runs ``--v=5``). We map that onto
stdlib logging: ``V(n)`` messages are emitted at DEBUG with a per-module
verbosity gate, so ``--v=5`` shows V(1)..V(5).
"""

from __future__ import annotations

import logging
import sys
from typing import Any

_VERBOSITY = 0


def setup(verbosity: int = 0, stream: Any = None) -> None:
    global _VERBOSITY
    _VERBOSITY = verbosity
    logging.basicConfig(
        level=logging.DEBUG if verbosity > 0 else logging.INFO,
        stream=stream or sys.stderr,
        format="%(levelname).1s%(asctime)s %(name)s] %(message)s",
        datefmt="%m%d %H:%M:%S",
        force=True,  # re-apply on verbosity reload / under pytest handlers
    )


def verbosity() -> int:
    return _VERBOSITY


class Logger:
    """Thin wrapper adding ``.v(n)`` gated verbose logging."""

    def __init__(self, name: str) -> None:
        self._log = logging.getLogger(name)

    def info(self, msg: str, *args: object) -> None:
        self._log.info(msg, *args)

    def warning(self, msg: str, *args: object) -> None:
        self._log.warning(msg, *args)

    def error(self, msg: str, *args: object) -> None:
        self._log.error(msg, *args)

    def fatal(self, msg: str, *args: object) -> None:
        self._log.critical(msg, *args)
        raise SystemExit(255)

    def v(self, level: int, msg: str, *args: object) -> None:
        if _VERBOSITY >= level:
            self._log.debug(msg, *args)


def get_logger(name: str) -> Logger:
    return Logger(name)
