"""Leveled logging with glog-style verbosity tiers and trace correlation.

The reference logs through glog with ``--v`` verbosity (V(1) lifecycle,
V(4)/V(6) per-decision detail; DaemonSet runs ``--v=5``). We map that onto
stdlib logging: ``V(n)`` messages are emitted at DEBUG with a per-module
verbosity gate, so ``--v=5`` shows V(1)..V(5).

Trace correlation: ``setup()`` installs a LogRecord factory that stamps
every record with the trace/span ids of the span current on the emitting
thread (``utils.tracing``), rendered as `` [trace/span]`` between the
logger name and the message — so a grep for one admission's trace id
pulls its log lines, and the flight recorder's ring keeps the ids in
structured form.

Fatal hooks: ``Logger.fatal`` runs registered hooks (the flight
recorder's dump-on-fatal) before raising SystemExit, so a dying daemon
leaves a postmortem behind.
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Callable

from . import tracing

_VERBOSITY = 0
_factory_installed = False
_fatal_hooks: list[Callable[[str], Any]] = []


def _install_record_factory() -> None:
    """Wrap the active LogRecord factory to add ``record.trace``: empty
    outside spans, `` [<trace8>/<span8>]`` inside a sampled one. Runs on
    every record so handlers installed before setup() see it too."""
    global _factory_installed
    if _factory_installed:
        return
    _factory_installed = True
    old_factory = logging.getLogRecordFactory()

    def factory(*args: Any, **kwargs: Any) -> logging.LogRecord:
        record = old_factory(*args, **kwargs)
        ids = tracing.current_trace_ids()
        record.trace = f" [{ids[0][:8]}/{ids[1][:8]}]" if ids else ""
        return record

    logging.setLogRecordFactory(factory)


def setup(verbosity: int = 0, stream: Any = None) -> None:
    global _VERBOSITY
    _VERBOSITY = verbosity
    _install_record_factory()
    logging.basicConfig(
        level=logging.DEBUG if verbosity > 0 else logging.INFO,
        stream=stream or sys.stderr,
        format="%(levelname).1s%(asctime)s %(name)s%(trace)s] %(message)s",
        datefmt="%m%d %H:%M:%S",
        force=True,  # re-apply on verbosity reload / under pytest handlers
    )


def verbosity() -> int:
    return _VERBOSITY


def on_fatal(hook: Callable[[str], Any]) -> None:
    """Register a hook run (with the fatal message) before a fatal exit."""
    _fatal_hooks.append(hook)


def clear_fatal_hooks() -> None:
    _fatal_hooks.clear()


class Logger:
    """Thin wrapper adding ``.v(n)`` gated verbose logging."""

    def __init__(self, name: str) -> None:
        self._log = logging.getLogger(name)

    def info(self, msg: str, *args: object) -> None:
        self._log.info(msg, *args)

    def warning(self, msg: str, *args: object) -> None:
        self._log.warning(msg, *args)

    def error(self, msg: str, *args: object) -> None:
        self._log.error(msg, *args)

    def fatal(self, msg: str, *args: object) -> None:
        self._log.critical(msg, *args)
        rendered = msg % args if args else msg
        for hook in list(_fatal_hooks):
            try:
                hook(rendered)
            except Exception as e:  # noqa: BLE001 — dying anyway; best effort
                self._log.error("fatal hook failed: %s", e)
        raise SystemExit(255)

    def v(self, level: int, msg: str, *args: object) -> None:
        if _VERBOSITY >= level:
            self._log.debug(msg, *args)


def get_logger(name: str) -> Logger:
    return Logger(name)
