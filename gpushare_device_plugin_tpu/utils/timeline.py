"""Cluster-state timeline: a compact time-bucketed ring of control-plane
health samples, exported on ``/timeline`` and embedded in flight-recorder
dumps.

A postmortem that only captures the instant of death explains the crash;
one that carries the minutes *before* it explains the cause. The
:class:`ClusterTimeline` folds periodic samples — utilization %,
fragmentation (``stranded_pct``), pending / gang-queue depth, SLO burn —
into fixed-width time buckets (last write per bucket wins), so an hour
of history is a few hundred floats regardless of sample rate. The ring
is hard-bounded by construction: a storm of samples can only overwrite
buckets, never grow the structure, and the field table is capped so a
storm of *distinct field names* cannot grow it either.

:class:`TimelineLoop` is the daemon-side sampler: a background thread
calling injected zero-argument sources each tick (utilization from the
pod source's chip state, stranded % from the defrag gauges, pending
depth from the informer index, burn from the SLO gauges) — all
read-only, all best-effort (a failing source skips its field, never the
tick).

``kubectl-inspect-tpushare timeline`` renders the series as sparklines;
``utils/flightrec.py`` embeds :meth:`ClusterTimeline.to_doc` in every
dump.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping

from .lockrank import make_lock
from .log import get_logger

log = get_logger("utils.timeline")

# Field-table hard bound: the sampler wires a handful of well-known
# fields; this exists so a misbehaving caller streaming unique field
# names cannot grow the ring's memory.
MAX_FIELDS = 32


class ClusterTimeline:
    """Fixed-bucket ring of named float series.

    ``bucket_s`` is the fold granularity, ``buckets`` the ring length
    (defaults: 10 s x 360 = one hour of history). Buckets between the
    last sample and ``now`` read as gaps (None), so a stalled sampler is
    visible as missing data, not as a frozen flat line."""

    def __init__(
        self,
        bucket_s: float = 10.0,
        buckets: int = 360,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if bucket_s <= 0:
            raise ValueError(f"bucket_s must be > 0, got {bucket_s}")
        if buckets < 1:
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        self._lock = make_lock("timeline.ring")
        self._bucket_s = bucket_s
        self._n = buckets
        self._clock = clock
        # field -> ring of values (None = no sample landed in the bucket)
        self._fields: dict[str, list[float | None]] = {}
        self._newest: int | None = None  # absolute bucket index
        self._dropped_fields = 0

    @property
    def bucket_s(self) -> float:
        return self._bucket_s

    @property
    def span_s(self) -> float:
        return self._bucket_s * self._n

    def _advance(self, bucket: int) -> None:
        """Blank the ring positions between the newest seen bucket and
        ``bucket`` (lock held) — time that passed without samples must
        read as gaps."""
        if self._newest is None:
            self._newest = bucket
            return
        gap = bucket - self._newest
        if gap <= 0:
            return
        for ring in self._fields.values():
            for i in range(1, min(gap, self._n) + 1):
                ring[(self._newest + i) % self._n] = None
        self._newest = bucket

    def sample(self, now: float | None = None, **fields: float) -> None:
        """Fold one sample set into the current bucket (last write per
        bucket wins — the series records state, not throughput)."""
        t = self._clock() if now is None else now
        bucket = int(t / self._bucket_s)
        with self._lock:
            self._advance(bucket)
            pos = bucket % self._n
            for name, value in fields.items():
                ring = self._fields.get(name)
                if ring is None:
                    if len(self._fields) >= MAX_FIELDS:
                        self._dropped_fields += 1
                        continue
                    ring = [None] * self._n
                    self._fields[name] = ring
                ring[pos] = float(value)

    def series(self, field: str) -> list[tuple[float, float]]:
        """(bucket start unix time, value) pairs for ``field``, oldest
        first, gaps omitted."""
        with self._lock:
            ring = self._fields.get(field)
            if ring is None or self._newest is None:
                return []
            out: list[tuple[float, float]] = []
            for age in range(self._n - 1, -1, -1):
                bucket = self._newest - age
                if bucket < 0:
                    continue
                value = ring[bucket % self._n]
                if value is None:
                    continue
                out.append((bucket * self._bucket_s, value))
            return out

    def fields(self) -> list[str]:
        with self._lock:
            return sorted(self._fields)

    def to_doc(self) -> dict[str, Any]:
        """The ``/timeline`` endpoint body (also embedded in flight-
        recorder dumps): bucket geometry plus every series as
        ``[[t, v], ...]``."""
        names = self.fields()
        return {
            "bucket_s": self._bucket_s,
            "span_s": self.span_s,
            "series": {
                name: [[t, v] for t, v in self.series(name)]
                for name in names
            },
        }

    def clear(self) -> None:
        with self._lock:
            self._fields.clear()
            self._newest = None
            self._dropped_fields = 0


class TimelineLoop:
    """Background sampler feeding a :class:`ClusterTimeline` from
    injected read-only sources.

    ``sources`` maps a label -> zero-arg callable returning either a
    float (single field, named by the label), None (skip this tick), or
    a mapping of field name -> float (a MULTI-FIELD source: one
    underlying read feeds several series — e.g. one pending-pod list
    yields both the total and the gang-queue depth, instead of two
    identical LISTs per tick). Sources are best-effort: one raising or
    returning garbage skips its fields, the rest of the tick proceeds —
    a sick apiserver must not blind the whole timeline."""

    def __init__(
        self,
        timeline: ClusterTimeline,
        sources: Mapping[str, Callable[[], "float | Mapping | None"]],
        interval_s: float = 10.0,
    ) -> None:
        self._timeline = timeline
        self._sources = dict(sources)
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run_once(self) -> dict[str, float]:
        """One sampling pass (the loop body; tests drive it directly)."""
        fields: dict[str, float] = {}
        for name, fn in self._sources.items():
            try:
                value = fn()
            except Exception as e:  # noqa: BLE001 — best-effort source
                log.v(4, "timeline source %s failed: %s", name, e)
                continue
            if value is None:
                continue
            items = (
                value.items() if isinstance(value, Mapping)
                else [(name, value)]
            )
            for field, v in items:
                try:
                    fields[str(field)] = float(v)
                except (TypeError, ValueError):
                    continue
        if fields:
            self._timeline.sample(**fields)
        return fields

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.run_once()

    def start(self) -> "TimelineLoop":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="timeline-sampler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# Process-wide default timeline, mirroring metrics.REGISTRY /
# tracing.STORE / decisions.DECISIONS.
TIMELINE = ClusterTimeline()
