"""Fleet binding: N paged engines behind the router, co-simulated.

``serving/router.py`` is the jax-free protocol half (routing table,
membership, journaled scale-down); this module binds it to real
:class:`~.engine.PagedSlotEngine` instances the way ``handoff.py``
binds ``handoffproto.py`` to a prefill/decode pair. One
:class:`FleetServer` owns a pool of engines, a
:class:`~.router.FleetMembership` scraping each engine's exported doc
(free slots, queue depth, radix prefix fingerprints — the /fleet
endpoint serves the same doc), a :class:`~.router.FleetRouter`, and a
:class:`~.router.ScaleExecutor` whose side-effect hooks are this
module's methods.

``serve`` is a co-simulation (the disagg server's style): the trace is
routed request by request in arrival order — affinity fingerprints and
load estimates updating as it goes — then each engine serves its
sub-trace. Three failure drills ride the same entry point:

- **scale-down** (``scale_down=(victim, at_tick)``): the victim drains
  at the tick mid-trace through the journaled cordon→drain→migrate→
  release protocol; its unfinished requests restore onto a survivor
  (``snapshot_id``-deduped), tokens bit-identical to an undisturbed
  run.
- **engine death** (``kill_engine=(victim, at_tick)``): the victim's
  snapshot dies with it — the ROUTER's in-flight table is the recovery
  source: unfinished requests re-queue as fresh admissions on
  survivors (full re-prefill; greedy determinism makes the tokens
  bit-identical), zero dropped.
- **router restart** (``restart_router_after=k``): the routing table is
  a cache of the engines' ground truth — a fresh router seeds its
  in-flight table from the buckets already committed and keeps
  routing; no request is lost or double-routed.

The reconciler hooks (:meth:`scale_deliver` / :meth:`scale_requeue`)
are what ``cluster/reconciler.py`` calls to resolve a scale WAL entry
found after a crash: roll-forward re-delivers the journaled snapshot
to a survivor, roll-back re-opens the replica or re-queues the
journaled rows — either way every request is served exactly once
(``tests/test_fleet.py`` pins every crash site).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from ..const import FLEET_REPLICA_DRAINING
from ..utils.decisions import DECISIONS, DecisionLog
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.slo import SloBudget
from .engine import PagedSlotEngine, Request, ServeStats
from .router import (
    EngineScrapeClient,
    FleetMembership,
    FleetRouter,
    ScaleExecutor,
)

log = get_logger("serving.fleet")


class FleetServer:
    """A pool of paged engines behind the prefix-affinity router."""

    def __init__(
        self,
        engines: Mapping[str, PagedSlotEngine],
        *,
        checkpoint: Any = None,
        assume: Any = None,
        policy: str = "prefix-affinity",
        slo_budget: SloBudget | None = None,
        shed_queue_depth: int = 64,
        miss_threshold: int = 3,
        decisions: DecisionLog = DECISIONS,
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
        node: str = "",
    ) -> None:
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        self.engines: dict[str, PagedSlotEngine] = dict(engines)
        first = next(iter(self.engines.values()))
        self.page_size = first.page_size
        self.membership = FleetMembership(
            miss_threshold=miss_threshold, registry=registry, pod=pod
        )
        for name, eng in sorted(self.engines.items()):
            # frozen clock + no-op sleep: the co-simulated scrape is
            # in-process and deterministic (the tpumc discipline)
            client = EngineScrapeClient(
                lambda n=name: self.scrape_doc(n),
                sleep=lambda s: None,
                clock=lambda: 0.0,
            )
            self.membership.add(name, client, capacity=eng.n_slots)
        self.router = FleetRouter(
            self.membership,
            page_size=self.page_size,
            policy=policy,
            slo_budget=slo_budget,
            shed_queue_depth=shed_queue_depth,
            decisions=decisions,
            registry=registry,
            pod=pod,
        )
        self.executor = ScaleExecutor(
            checkpoint, assume,
            cordon_fn=self._cordon,
            rows_fn=self._frozen_rows,
            drain_fn=self._drain_victim,
            migrate_fn=self._migrate_snapshot,
            release_fn=self._release_victim,
            node=node, registry=registry, pod=pod,
        )
        self._decisions = decisions
        self._registry = registry
        self._pod = pod
        # accumulated across serve()/resolve passes — the exactly-once
        # ledger the chaos gates assert on
        self.results: dict[int, dict] = {}
        self.double_served: list[int] = []
        self.shed: list[int] = []
        self._requests: dict[int, Request] = {}
        self._buckets: dict[str, list[Request]] = {}
        self._scale_tick: int | None = None

    # --- the per-engine exported doc (the /fleet scrape plane) ------------

    def scrape_doc(self, name: str) -> dict[str, Any]:
        """One engine's membership doc: headroom + prefix fingerprints.
        Raises when the replica is gone — a scrape miss, which is the
        failure detector's signal, not an error to hide."""
        eng = self.engines.get(name)
        if eng is None:
            raise LookupError(f"fleet replica {name} is gone")
        fps = eng.radix.fingerprints() if eng.radix is not None else []
        return {
            "free_slots": eng.n_slots,
            "capacity": eng.n_slots,
            "queue_depth": 0,
            "fingerprints": fps,
            "page_size": eng.page_size,
        }

    def fleet_doc(self) -> dict[str, Any]:
        """The /fleet endpoint's document (``kubectl-inspect-tpushare
        fleet`` renders it): replica map, router outcomes, scale state,
        and the global prefix-hit ratio."""
        return {
            "replicas": self.membership.doc()["replicas"],
            "router": self.router.doc(),
            "scale": {
                "ops": self.executor.completed_ops,
                "migrated_requests": self.executor.migrated_requests,
            },
            "prefix_hit_ratio": round(self.prefix_hit_ratio(), 4),
        }

    def prefix_hit_ratio(self) -> float:
        """Fleet-global radix hit ratio: summed hit tokens over summed
        lookup tokens across every engine (not an average of ratios —
        a busy engine weighs more)."""
        hit = looked = 0
        for eng in self.engines.values():
            if eng.radix is not None:
                hit += eng.radix.hit_tokens
                looked += eng.radix.lookup_tokens
        return hit / looked if looked else 0.0

    # --- serve: route, then run ------------------------------------------

    def serve(
        self,
        requests: Sequence[Request],
        *,
        scale_down: tuple[str, int] | None = None,
        kill_engine: tuple[str, int] | None = None,
        restart_router_after: int | None = None,
        scale_id: str = "scale-0",
    ) -> dict:
        """Route the trace and serve it across the pool; see the module
        docstring for the three failure drills. Returns the merged
        result doc (rid -> tokens/latency/engine/path, shed and dropped
        lists, router/membership docs)."""
        self.membership.scrape_once()
        incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
        for i, r in enumerate(incoming):
            if restart_router_after is not None and i == restart_router_after:
                self._restart_router()
            self._requests[r.rid] = r
            d = self.router.route(str(r.rid), r.prompt, r.tier)
            if d.engine is None:
                if d.shed:
                    self.shed.append(r.rid)
                    continue
                raise RuntimeError(
                    f"request {r.rid} unroutable: {d.reason}"
                )
            self._buckets.setdefault(d.engine, []).append(r)
        if scale_down is not None:
            victim, at_tick = scale_down
            self._scale_tick = at_tick
            self.executor.execute(scale_id, victim)
            self._scale_tick = None
        elif kill_engine is not None:
            self._kill_engine(*kill_engine)
        for name in sorted(self._buckets):
            bucket = self._buckets.pop(name)
            if not bucket:
                continue
            eng = self.engines.get(name)
            if eng is None:
                # released mid-serve (scale-down raced a late bucket)
                self._requeue_rows(
                    [self._row_of(r) for r in bucket], path="requeued"
                )
                continue
            stats = eng.run(bucket)
            self._record(stats, name, "fleet")
        self.membership.scrape_once()
        self.membership.publish()
        return self._finish(incoming)

    def _restart_router(self) -> None:
        """Replace the router mid-trace (crash drill): the new table
        seeds from the engines' ground truth — here, the buckets of
        requests already committed to an engine."""
        self.router = FleetRouter(
            self.membership,
            page_size=self.page_size,
            policy=self.router._policy,
            slo_budget=self.router._slo,
            shed_queue_depth=self.router._shed_queue_depth,
            decisions=self._decisions,
            registry=self._registry,
            pod=self._pod,
        )
        self.router.seed_inflight({
            str(r.rid): name
            for name, bucket in self._buckets.items()
            for r in bucket
        })

    def _row_of(self, r: Request) -> dict:
        return {
            "rid": r.rid,
            "state": "queued",
            "prompt": list(r.prompt),
            "max_new": r.max_new,
            "arrival": float(r.arrival),
            "tier": r.tier,
            "slo_ttft_ticks": r.slo_ttft_ticks,
            "slo_tpot_ticks": r.slo_tpot_ticks,
            "tokens": [],
        }

    def _record(self, stats: ServeStats, engine: str, path: str) -> None:
        for res in stats.results:
            start = res.arrival_tick
            req = self._requests.get(res.rid)
            if req is not None:
                start = req.arrival
            n = len(res.tokens)
            entry = {
                "tokens": list(res.tokens),
                "ttft_ticks": (
                    res.first_token_tick - float(start)
                    if res.first_token_tick >= 0 else None
                ),
                "tpot_ticks": (
                    (res.finish_tick - float(start)) / (n - 1)
                    if n > 1 and res.finish_tick >= 0 else None
                ),
                "engine": engine,
                "path": path,
            }
            if res.rid in self.results:
                self.double_served.append(res.rid)
                log.warning("fleet served rid %d twice", res.rid)
            self.results[res.rid] = entry
            self.router.complete(str(res.rid))

    def _finish(self, requests: Sequence[Request]) -> dict:
        admitted = [r for r in requests if r.rid not in self.shed]
        dropped = [
            r.rid for r in admitted
            if r.rid not in self.results
            or not self.results[r.rid]["tokens"]
        ]
        if dropped:
            log.warning("fleet serve dropped rids %s", dropped)
        return {
            "results": dict(self.results),
            "shed": list(self.shed),
            "dropped": dropped,
            "double_served": list(self.double_served),
            "router": self.router.doc(),
            "replicas": self.membership.doc()["replicas"],
            "prefix_hit_ratio": self.prefix_hit_ratio(),
        }

    # --- scale-down side-effect hooks (ScaleExecutor) ---------------------

    def _cordon(self, victim: str) -> None:
        self.membership.cordon(victim)

    def _frozen_rows(self, victim: str) -> list[dict]:
        """The victim's frozen in-flight set, post-cordon: everything
        routed to it and not yet served (JSON-safe — it goes straight
        into the drain record)."""
        return [
            self._row_of(r)
            for r in self._buckets.get(victim, ())
            if r.rid not in self.results
        ]

    def _drain_victim(self, victim: str) -> dict:
        self.membership.set_state(victim, FLEET_REPLICA_DRAINING)
        eng = self.engines[victim]
        bucket = self._buckets.pop(victim, [])
        stats = eng.run(bucket, drain_at_tick=self._scale_tick)
        self._record(stats, victim, "drained")
        return eng.drain_snapshot() or {}

    def _migrate_snapshot(self, snapshot: dict, record: dict) -> int:
        rows = (snapshot or {}).get("requests") or []
        if not rows:
            return 0
        survivor = self.router.least_loaded(
            exclude={str(record.get("engine") or "")}
        )
        if survivor is None:
            raise RuntimeError(
                "scale migrate: no ready survivor — entry stays pending"
            )
        stats = self.engines[survivor].restore_snapshot(snapshot)
        self._record(stats, survivor, "migrated")
        for row in rows:
            self.router.complete(str(row["rid"]))
        return len(rows)

    def _release_victim(self, victim: str) -> None:
        self.membership.mark_dead(victim)
        self.router.forget_engine(victim)
        self.engines.pop(victim, None)

    # --- reconciler hooks (resolve_scale's side effects) ------------------

    def scale_deliver(self, scale_id: str, record: dict) -> None:
        """Roll-forward: re-deliver the journaled snapshot to a
        survivor (idempotent — restore dedups by snapshot_id) and
        finish the release the dead executor never reached."""
        self._migrate_snapshot(record.get("snapshot") or {}, record)
        victim = str(record.get("engine") or "")
        if victim:
            self._release_victim(victim)

    def scale_requeue(self, scale_id: str, record: dict) -> None:
        """Roll-back: the replica re-opens if it still lives; a dead
        one's journaled rows re-queue on survivors (rid-deduped against
        already-served results — full re-prefill, tokens bit-identical
        by greedy determinism)."""
        victim = str(record.get("engine") or "")
        if victim in self.engines:
            self.membership.uncordon(victim)
            return
        self._requeue_rows(record.get("rows") or [], path="requeued")

    # --- engine death ------------------------------------------------------

    def _kill_engine(self, victim: str, at_tick: int) -> None:
        """Simulate the victim dying mid-decode: results already
        streamed count as served; its KV (and any would-be snapshot)
        dies with it. Recovery is the router's in-flight table: every
        unfinished request re-queues as a fresh admission on the
        survivors."""
        eng = self.engines.pop(victim)
        bucket = self._buckets.pop(victim, [])
        stats = eng.run(bucket, drain_at_tick=at_tick)
        self._record(stats, victim, "fleet")
        self.membership.mark_dead(victim)
        rids = self.router.forget_engine(victim)
        rows = [
            self._row_of(self._requests[int(rid)])
            for rid in rids
            if int(rid) in self._requests
            and int(rid) not in self.results
        ]
        log.warning(
            "fleet replica %s died at tick %d; re-queueing %d in-flight "
            "requests on survivors", victim, at_tick, len(rows),
        )
        self._requeue_rows(rows, path="requeued")

    def _requeue_rows(self, rows: Sequence[dict], path: str) -> None:
        """Re-admit journaled/forgotten rows on live replicas, deduped
        by rid against everything already served."""
        groups: dict[str, list[Request]] = {}
        for row in rows:
            rid = int(row["rid"])
            if rid in self.results:
                continue
            req = Request(
                rid=rid,
                prompt=tuple(int(t) for t in row["prompt"]),
                max_new=int(row["max_new"]),
                arrival=0.0,  # re-queued requests have already arrived
                tier=str(row.get("tier") or "critical"),
                slo_ttft_ticks=row.get("slo_ttft_ticks"),
                slo_tpot_ticks=row.get("slo_tpot_ticks"),
            )
            self._requests.setdefault(rid, req)
            d = self.router.route(str(rid), req.prompt, req.tier)
            if d.engine is None:
                raise RuntimeError(
                    f"requeue of rid {rid} unroutable: {d.reason}"
                )
            groups.setdefault(d.engine, []).append(req)
        for name in sorted(groups):
            stats = self.engines[name].run(groups[name])
            self._record(stats, name, path)
