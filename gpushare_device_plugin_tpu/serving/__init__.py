"""Serving layer: the continuous-batching engine over a slot-pool KV cache.

``engine`` is the subsystem the HBM slices exist for: requests are
admitted into fixed KV-cache slots and retired per decode step, with
chunked prefill interleaved between decode steps — see
``docs/serving.md`` (continuous batching) and ``workloads/generate.py``
for the slot-cache primitives it composes.
"""

from .engine import (  # noqa: F401
    Request,
    RequestResult,
    ServeStats,
    SlotEngine,
    kv_slot_bytes,
    poisson_trace,
    run_static_baseline,
    slots_for_gang,
    slots_for_slice,
    slots_from_pod_env,
)
