"""Serving layer: the continuous-batching engine over a slot-pool KV cache.

``engine`` is the subsystem the HBM slices exist for: requests are
admitted into fixed KV-cache slots and retired per decode step, with
chunked prefill interleaved between decode steps — see
``docs/serving.md`` (continuous batching) and ``workloads/generate.py``
for the slot-cache primitives it composes. ``pages`` + ``radix`` +
:class:`~.engine.PagedSlotEngine` replace the per-request ``max_len``
row with reference-counted fixed-size KV pages, a shared-prefix radix
cache, and SLO-tiered admission with best-effort preemption
(``docs/serving.md``, paged KV section). The paged engine optionally
runs a draft model out of the same refcounted pool for greedy
speculative decoding — draft proposes k tokens per slot, target
verifies the block in one forward, accept/rollback by page refcount
keeps tokens bit-identical to plain decode (``docs/serving.md``,
speculative section). ``profiler`` + ``governor``
are the serving half of the interference observability plane: per-slice
decode-step profiling and the Tally-style best-effort step throttle
(``docs/observability.md``, interference plane).

``handoffproto`` + ``handoff`` split the engine into a prefill tier and
a decode tier: the journaled export→transfer→import→commit KV-handoff
protocol (jax-free core, model-checked by ``tools/tpumc``) and its
engine binding — page serialization, the :class:`~.handoff.DisaggServer`
two-tier plane with the re-prefill degradation ladder
(``docs/serving.md``, disaggregation section).

``adapters`` is the multi-tenant LoRA plane: per-tenant low-rank
fine-tunes live as paged tensors in the SAME refcounted page pool as KV
and draft KV (:class:`~.adapters.AdapterCache` — refcount-pinned while
any slot uses them, LRU-evicted below KV in the ladder, SLO-tier
shielded), and the paged engine decodes a batch mixing ANY number of
distinct adapters in one gathered BGMV dispatch — adapter identity is
page-table data, never a shape, and greedy tokens stay bit-identical to
``merge_lora`` + solo generate (``docs/serving.md``, multi-LoRA
section).

``router`` + ``fleet`` put a pool of paged engines behind one front
door: the prefix-affinity :class:`~.router.FleetRouter` (radix
fingerprints via the metrics plane, SLO-aware best-effort shedding,
health-checked membership with consecutive-miss eviction) and the
journaled cordon→drain→migrate→release scale-down protocol (jax-free
core like ``handoffproto``; engine binding
:class:`~.fleet.FleetServer`) — an engine dies or scales away, its
in-flight requests land on a survivor with tokens bit-identical and
zero dropped (``docs/serving.md``, fleet section).
"""

from .adapters import AdapterCache  # noqa: F401
from .engine import (  # noqa: F401
    TIER_BEST_EFFORT,
    TIER_CRITICAL,
    PagedSlotEngine,
    Request,
    RequestResult,
    ServeStats,
    SlotEngine,
    kv_slot_bytes,
    paged_plan_from_pod_env,
    poisson_trace,
    run_static_baseline,
    shared_prefix_trace,
    slots_for_gang,
    slots_for_slice,
    slots_from_pod_env,
)
from .governor import StepGovernor  # noqa: F401
from .handoff import (  # noqa: F401
    BrokenTransport,
    DisaggServer,
    build_handoff_plan,
    decode_page,
    encode_page,
)
from .handoffproto import (  # noqa: F401
    HANDOFF_KIND,
    HANDOFF_PHASES,
    HandoffImportLedger,
    HandoffMover,
    HandoffPeerClient,
    HandoffPlan,
    HandoffSink,
    resolve_handoff,
)
from .fleet import FleetServer  # noqa: F401
from .pages import (  # noqa: F401
    PageAllocator,
    PagedPlan,
    paged_plan_for_slice,
    pages_for,
)
from .profiler import StepProfiler  # noqa: F401
from .radix import RadixCache, prefix_fingerprints  # noqa: F401
from .router import (  # noqa: F401
    SCALE_KIND,
    SCALE_PHASES,
    EngineScrapeClient,
    FleetMembership,
    FleetRouter,
    RouteDecision,
    ScaleExecutor,
    resolve_scale,
    scale_key,
)
