"""Continuous-batching serving engine: slot-based KV cache, prefill/decode
interleaving, slice-aware admission.

The static path (``workloads.generate.generate``) is lockstep: every
request in a batch runs all ``max_new`` steps and nothing is admitted
until the whole batch retires — short requests subsidize long ones and a
queued request's TTFT is a full batch lifetime. This engine replaces the
batch with a fixed pool of KV-cache **slots** (``init_slot_cache``):

- **Admission** packs a waiting request into any free slot row via
  :func:`~..workloads.generate.prefill_slot` /
  :func:`~..workloads.generate.extend_slot` — chunked prefill, one fixed
  -width chunk between decode steps, so in-flight slots keep decoding
  while a newcomer's prompt streams in.
- **Decode** advances every occupied slot one token per step through the
  per-slot :func:`~..workloads.generate.decode_step` (vector ``len``);
  a slot that emits EOS (or exhausts its ``max_new``) retires and frees
  its row IMMEDIATELY — the next step can admit into it.
- **Static shapes throughout**: the pool, chunk width, and step batch
  never change shape, so XLA compiles exactly three programs (fresh-slot
  prefill, continuation chunk, decode step) once each; slot churn
  performs zero retraces (``trace_counts``, guarded in tests and the
  serve bench).

Greedy decoding only: the engine's contract is that every request's
tokens are bit-identical to a solo greedy ``generate()`` call — the
property the serving-correctness tests pin, and what makes goodput
comparisons against the static baseline apples-to-apples.

**Clocks.** Arrivals and latencies are tracked on two clocks: wall
seconds, and *ticks* — one tick per model dispatch (a prefill chunk or
one pool-wide decode step). The tick clock is deterministic (no timer
jitter), so the smoke test's continuous-vs-static guards can be exact;
wall numbers are what the bench reports.

**Slice-aware sizing.** :func:`slots_for_slice` derives the slot-pool
size from a pod's ``aliyun.com/tpu-mem`` HBM slice (weights + per-slot
KV bytes + headroom), and :func:`slots_from_pod_env` reads the slice
straight from the plugin-injected container env — the loop back to the
device plugin this repo exists for (``docs/serving.md`` sizing table).
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..const import MemoryUnit
from ..parallel.podenv import PodTpuEnv
from ..utils.tracing import TRACER
from ..workloads import generate as G
from ..workloads.transformer import TransformerConfig, shard_params


@dataclasses.dataclass(frozen=True)
class Request:
    """One serving request (host-side). ``arrival`` is in engine ticks."""

    rid: int
    prompt: tuple[int, ...]
    max_new: int
    arrival: float = 0.0

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new < 1:
            raise ValueError(f"request {self.rid}: max_new must be >= 1")


@dataclasses.dataclass
class RequestResult:
    """Per-request outcome + latency telemetry (both clocks)."""

    rid: int
    prompt_len: int
    tokens: list[int]
    arrival_tick: float
    first_token_tick: int = -1
    finish_tick: int = -1
    arrival_s: float = 0.0
    first_token_s: float = 0.0
    finish_s: float = 0.0
    # slot admission (end of queue wait), both clocks
    admit_tick: int = -1
    admit_s: float = 0.0
    # the request's serve trace (utils.tracing), "" when unsampled
    trace_id: str = ""

    @property
    def ttft_ticks(self) -> float:
        return self.first_token_tick - self.arrival_tick

    @property
    def ttft_s(self) -> float:
        return self.first_token_s - self.arrival_s


@dataclasses.dataclass
class ServeStats:
    """One serving run's results + aggregate metrics."""

    results: list[RequestResult]
    ticks: int
    wall_s: float
    trace_counts: dict[str, int]

    @staticmethod
    def _quantile(vals: list[float], q: float) -> float:
        if not vals:
            return float("nan")
        s = sorted(vals)
        return s[min(len(s) - 1, int(math.ceil(q * len(s))) - 1)]

    def summary(self) -> dict:
        """Flat metrics dict (the serve bench's report row)."""
        tokens = sum(len(r.tokens) for r in self.results)
        ttft_t = [r.ttft_ticks for r in self.results]
        ttft_s = [r.ttft_s for r in self.results]
        return {
            "requests": len(self.results),
            "tokens": tokens,
            "ticks": self.ticks,
            "wall_s": round(self.wall_s, 4),
            # Goodput: completed requests' generated tokens over makespan
            # (post-EOS padding never exists here — retirement is
            # immediate — so every counted token is useful).
            "goodput_tokens_per_s": round(tokens / self.wall_s, 1)
            if self.wall_s > 0 else None,
            "goodput_tokens_per_tick": round(tokens / max(self.ticks, 1), 3),
            "ttft_p50_ticks": self._quantile(ttft_t, 0.50),
            "ttft_p99_ticks": self._quantile(ttft_t, 0.99),
            "ttft_p50_ms": round(self._quantile(ttft_s, 0.50) * 1e3, 2),
            "ttft_p99_ms": round(self._quantile(ttft_s, 0.99) * 1e3, 2),
            "trace_counts": dict(self.trace_counts),
        }


@dataclasses.dataclass
class _Slot:
    state: str = "free"  # free | prefill | decode
    req: Request | None = None
    done: int = 0  # prompt tokens prefilled so far
    last: int = 0  # last sampled token (decode input)
    result: RequestResult | None = None


class SlotEngine:
    """Continuous-batching engine over ``slots`` KV-cache rows.

    ``prefill_chunk`` is the static prompt-chunk width (admission cost
    granularity); ``max_len`` bounds each slot row (prompt + generated).
    Admission is slice-aware up front: a request whose
    ``prompt + max_new`` cannot fit a slot row is rejected at submit
    time instead of overflowing mid-decode.
    """

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        *,
        slots: int,
        max_len: int,
        prefill_chunk: int = 64,
        eos_id: int | None = None,
        kv_dtype: str | None = None,
        mesh=None,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if max_len > cfg.max_seq:
            raise ValueError(
                f"max_len {max_len} exceeds cfg.max_seq {cfg.max_seq} "
                "(RoPE table bound)"
            )
        if prefill_chunk > max_len:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} exceeds the slot row "
                f"({max_len} positions) — even one chunk cannot be packed"
            )
        self.params = params
        self.cfg = cfg
        self.n_slots = slots
        self.max_len = max_len
        self.chunk = prefill_chunk
        self.eos_id = eos_id
        self.cache = G.init_slot_cache(cfg, slots, max_len, kv_dtype=kv_dtype)
        # Tensor-parallel serving across a granted gang: with a mesh (from
        # ``parallel.podenv.gang_mesh`` inside a multi-chip grant), the
        # model weights shard per ``transformer.param_specs`` (heads /
        # mlp-hidden / vocab over tp) and the slot-pool KV cache shards
        # its kv-heads dimension over the same axis — every chip of the
        # gang holds 1/tp of the weights and 1/tp of every slot row, and
        # XLA inserts the psums over the gang's ICI sub-slice (the
        # NamedSharding/GSPMD pattern; nothing here hand-schedules
        # communication). The engine's host loop, static shapes, and
        # compile-count guarantees are unchanged: sharding is a layout
        # property of the same three programs.
        self.mesh = mesh if mesh is not None and mesh.shape.get("tp", 1) > 1 else None
        if self.mesh is not None:
            self.params = shard_params(self.params, self.mesh, cfg)
            self.cache = self._shard_cache(self.cache)
        self.ticks = 0
        # One entry per compiled program; a counting wrapper increments at
        # TRACE time, so steady-state slot churn must leave these frozen
        # (the no-retrace guard the tests and serve bench assert).
        self.trace_counts = {"prefill": 0, "extend": 0, "decode": 0}
        self._build_fns()

    def _shard_cache(self, cache):
        """Place the slot-pool cache tensor-parallel: K/V (and int8
        scales) shard their kv-heads axis over tp — each gang chip pins
        ``kv_slot_bytes / tp`` per row, which is what lets a gang's
        per-chip HBM share hold a pool no single chip could
        (:func:`slots_for_gang`). A kv-head count tp does not divide
        falls back to replication for that buffer (the
        ``prune_unshardable`` rule), keeping correctness over memory."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = self.mesh.shape["tp"]
        divisible = self.cfg.kv_heads % tp == 0

        def spec_for(name: str, ndim: int):
            if name == "len" or not divisible:
                return P()
            # k/v: [L, slots, max_len, Hkv, Dh]; scales: [L, slots, max_len, Hkv]
            parts = [None] * ndim
            parts[3] = "tp"
            return P(*parts)

        return {
            name: jax.device_put(
                val, NamedSharding(self.mesh, spec_for(name, val.ndim))
            )
            for name, val in cache.items()
        }

    def _build_fns(self) -> None:
        cfg = self.cfg

        def prefill_fn(params, tokens, cache, slot, n_real):
            self.trace_counts["prefill"] += 1
            logits, cache = G.prefill_slot(
                params, tokens, cache, cfg, slot=slot, n_real=n_real
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

        def extend_fn(params, tokens, cache, slot, n_real):
            self.trace_counts["extend"] += 1
            logits, cache = G.extend_slot(
                params, tokens, cache, cfg, slot=slot, n_real=n_real
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

        def decode_fn(params, tokens, cache, active):
            self.trace_counts["decode"] += 1
            logits, new = G.decode_step(params, tokens, cache, cfg)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            # Idle rows (free slots, mid-prefill slots) must not advance:
            # freeze their lengths so the next chunk/decode write lands
            # where the slot's real content ends.
            new = {**new, "len": jnp.where(active, new["len"], cache["len"])}
            return nxt, new

        # Caches are donated: the engine holds the only reference, and a
        # slot pool big enough to matter should not be double-buffered in
        # HBM on every step.
        self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))
        self._extend = jax.jit(extend_fn, donate_argnums=(2,))
        self._decode = jax.jit(decode_fn, donate_argnums=(2,))

    def warmup(self) -> None:
        """Compile all three programs off the clock (fresh-slot prefill,
        continuation chunk, decode step) so a timed :meth:`run` starts
        warm — the serving analog of the bench's warmup iterations. Slot
        0's row is scribbled on, which is safe by the visibility
        invariant; the tick clock is reset afterwards."""
        # chunk + 1 tokens forces the continuation (extend) trace too,
        # when the pool is big enough to ever admit a multi-chunk prompt
        # (same footprint rule as validate).
        plen = self.chunk + 1
        if max(2 * self.chunk, plen + 2) > self.max_len:
            plen = min(self.chunk, self.max_len - 2)
        self.run([Request(rid=-1, prompt=tuple(range(1, plen + 1)),
                          max_new=2, arrival=0.0)])
        self.ticks = 0

    def validate(self, req: Request) -> None:
        # Every prefill write is a FULL chunk (static width; the pad tail
        # is invisible), so the prompt's footprint is its chunk-padded
        # length: a final chunk that straddled the row end would make
        # dynamic_update_slice clamp the write start BACKWARDS over
        # already-cached positions — silent KV corruption, not an error.
        plen = len(req.prompt)
        padded = -(-plen // self.chunk) * self.chunk
        need = max(padded, plen + req.max_new)
        if need > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {plen} (chunk-padded {padded}) "
                f"+ max_new {req.max_new} needs {need} positions, exceeding "
                f"the slot row ({self.max_len}) — size the pool for the "
                "workload or reject upstream (slice-aware admission)"
            )

    def _chunk_arrays(self, req: Request, done: int) -> tuple[jax.Array, int]:
        real = req.prompt[done : done + self.chunk]
        buf = np.zeros((self.chunk,), np.int32)
        buf[: len(real)] = real
        return jnp.asarray(buf), len(real)

    def _record_request_trace(self, res: RequestResult, base_ns: int) -> None:
        """Emit the request's span timeline (queue wait -> prefill chunks
        -> decode steps -> retire) into the process trace store.

        Reconstructed from the timestamps the engine already collects, at
        retire time only — the per-token hot loop pays zero tracing cost
        and the compile-count/bit-identity guarantees are untouched.
        Unsampled requests (``TRACER.sample_ratio``) record nothing; the
        warmup's synthetic request (rid < 0) is skipped."""
        if res.rid < 0:
            return

        def at(seconds: float) -> int:
            return base_ns + int(seconds * 1e9)

        ctx = TRACER.record_span(
            "serve.request", at(res.arrival_s), at(res.finish_s),
            attributes={
                "rid": res.rid,
                "prompt_len": res.prompt_len,
                "tokens": len(res.tokens),
                "ttft_ticks": res.ttft_ticks,
                "slots": self.n_slots,
            },
        )
        if ctx is None:
            return
        res.trace_id = ctx.trace_id
        admit = res.admit_s if res.admit_tick >= 0 else res.arrival_s
        TRACER.record_span(
            "serve.queue", at(res.arrival_s), at(admit), parent=ctx,
            attributes={"wait_ticks": max(0, res.admit_tick - res.arrival_tick)},
        )
        chunks = -(-res.prompt_len // self.chunk)
        TRACER.record_span(
            "serve.prefill", at(admit), at(res.first_token_s), parent=ctx,
            attributes={"chunks": chunks, "chunk_width": self.chunk},
        )
        TRACER.record_span(
            "serve.decode", at(res.first_token_s), at(res.finish_s),
            parent=ctx,
            attributes={"decode_steps": max(0, len(res.tokens) - 1)},
        )
        TRACER.record_span(
            "serve.retire", at(res.finish_s), at(res.finish_s), parent=ctx,
            attributes={"finish_tick": res.finish_tick},
        )

    def run(self, requests: Sequence[Request]) -> ServeStats:
        """Serve ``requests`` to completion; returns results + metrics.

        The loop per iteration: (1) move arrived requests to the pending
        queue, (2) admit pending requests into free slots, (3) run ONE
        prompt chunk for the oldest mid-prefill slot (chunked prefill —
        bounded interference with decoding neighbors), (4) run one decode
        step across all decoding slots. Each model dispatch advances the
        tick clock by one.
        """
        for r in requests:
            self.validate(r)
        self.ticks = 0  # arrivals are relative to this run's start
        incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
        slots = [_Slot() for _ in range(self.n_slots)]
        pending: deque[Request] = deque()
        results: list[RequestResult] = []
        live: dict[int, RequestResult] = {}
        i = 0
        t0 = time.perf_counter()
        base_ns = time.time_ns()  # wall anchor for the request spans

        def now() -> float:
            return time.perf_counter() - t0

        def retire(idx: int) -> None:
            s = slots[idx]
            s.result.finish_tick = self.ticks
            s.result.finish_s = now()
            results.append(s.result)
            self._record_request_trace(s.result, base_ns)
            slots[idx] = _Slot()

        while i < len(incoming) or pending or any(
            s.state != "free" for s in slots
        ):
            while i < len(incoming) and incoming[i].arrival <= self.ticks:
                req = incoming[i]
                live[req.rid] = RequestResult(
                    rid=req.rid, prompt_len=len(req.prompt), tokens=[],
                    arrival_tick=req.arrival, arrival_s=now(),
                )
                pending.append(req)
                i += 1
            busy = any(s.state != "free" for s in slots)
            if not busy and not pending:
                # Pool idle, nothing queued: jump the tick clock to the
                # next arrival instead of spinning.
                self.ticks = max(self.ticks, int(math.ceil(incoming[i].arrival)))
                continue

            for idx, s in enumerate(slots):
                if s.state == "free" and pending:
                    req = pending.popleft()
                    res = live[req.rid]
                    res.admit_tick = self.ticks
                    res.admit_s = now()
                    slots[idx] = _Slot(
                        state="prefill", req=req, done=0, result=res
                    )

            pre = [idx for idx, s in enumerate(slots) if s.state == "prefill"]
            if pre:
                idx = min(pre, key=lambda j: slots[j].result.arrival_tick)
                s = slots[idx]
                tokens, n_real = self._chunk_arrays(s.req, s.done)
                fn = self._prefill if s.done == 0 else self._extend
                tok, self.cache = fn(
                    self.params, tokens, self.cache,
                    np.int32(idx), np.int32(n_real),
                )
                self.ticks += 1
                s.done += n_real
                if s.done == len(s.req.prompt):
                    first = int(tok)
                    s.result.first_token_tick = self.ticks
                    s.result.first_token_s = now()
                    s.result.tokens.append(first)
                    if (
                        self.eos_id is not None and first == self.eos_id
                    ) or s.req.max_new == 1:
                        retire(idx)
                    else:
                        s.state = "decode"
                        s.last = first

            dec = [idx for idx, s in enumerate(slots) if s.state == "decode"]
            if dec:
                toks = np.zeros((self.n_slots,), np.int32)
                active = np.zeros((self.n_slots,), bool)
                for idx in dec:
                    toks[idx] = slots[idx].last
                    active[idx] = True
                nxt, self.cache = self._decode(
                    self.params, jnp.asarray(toks), self.cache,
                    jnp.asarray(active),
                )
                self.ticks += 1
                nxt = np.asarray(nxt)
                for idx in dec:
                    s = slots[idx]
                    t = int(nxt[idx])
                    s.result.tokens.append(t)
                    s.last = t
                    if (
                        self.eos_id is not None and t == self.eos_id
                    ) or len(s.result.tokens) >= s.req.max_new:
                        retire(idx)

        results.sort(key=lambda r: r.rid)
        return ServeStats(
            results=results, ticks=self.ticks,
            wall_s=time.perf_counter() - t0,
            trace_counts=dict(self.trace_counts),
        )


# ---------------------------------------------------------------------------
# arrival drivers
# ---------------------------------------------------------------------------


def poisson_trace(
    n: int,
    *,
    seed: int,
    rate: float,
    vocab: int,
    prompt_lens: tuple[int, int],
    max_new: tuple[int, int] | Sequence[int],
) -> list[Request]:
    """Mixed-length Poisson arrival trace: exponential inter-arrival gaps
    at ``rate`` requests/tick, prompt lengths uniform over the (lo, hi)
    inclusive range. ``max_new`` as a TUPLE draws uniformly over the
    (lo, hi) range; a list draws from it as CHOICES — the
    serving-realistic bimodal mix (many short answers, a few long
    generations, e.g. ``[4, 4, 4, 40]``) that exposes lockstep's
    short-subsidizes-long waste. The type, not the length, disambiguates
    — a two-mode choices list like ``[4, 40]`` stays expressible.
    Deterministic per seed — the replay driver is ``[Request(...)]``
    literals."""
    if isinstance(max_new, tuple) and len(max_new) != 2:
        raise ValueError(
            f"max_new tuple must be (lo, hi), got {max_new!r}; pass a list "
            "for a choices mix"
        )
    rng = np.random.RandomState(seed)
    choices = None if isinstance(max_new, tuple) else list(max_new)
    t = 0.0
    out = []
    for rid in range(n):
        t += float(rng.exponential(1.0 / rate))
        plen = int(rng.randint(prompt_lens[0], prompt_lens[1] + 1))
        mn = (
            int(choices[rng.randint(len(choices))]) if choices is not None
            else int(rng.randint(max_new[0], max_new[1] + 1))
        )
        out.append(
            Request(
                rid=rid,
                prompt=tuple(int(x) for x in rng.randint(0, vocab, size=plen)),
                max_new=mn,
                arrival=t,
            )
        )
    return out


# ---------------------------------------------------------------------------
# static lockstep baseline
# ---------------------------------------------------------------------------


def run_static_baseline(
    params,
    cfg: TransformerConfig,
    requests: Sequence[Request],
    *,
    batch: int,
    eos_id: int | None = None,
    kv_dtype: str | None = None,
    warmup: bool = True,
    trials: int = 1,
) -> ServeStats:
    """The pre-engine serving discipline, instrumented for comparison:
    waves of up to ``batch`` requests run lockstep through ``generate()``
    (one padded prefill + ``max_new`` decode steps for EVERYONE), and
    nothing is admitted until the whole wave retires.

    Fair-but-generous accounting: a wave is taken the moment the pool is
    idle from whatever has ARRIVED (no waiting to fill the batch), the
    whole wave's prefill costs one tick (the engine pays one per chunk),
    and every wave decodes the GLOBAL max_new (lockstep cannot stop
    early — that is the point) at one tick per step. A member's tokens
    only exist when the batch call returns, so TTFT = wave completion −
    arrival on both clocks: the full-batch-lifetime TTFT the engine
    exists to fix. Tokens are truncated to each request's own
    ``max_new``/EOS so goodput counts the same useful tokens the engine
    produces (bit-identical, pinned by tests)."""
    gmax = max(r.max_new for r in requests)
    tp_max = max(len(r.prompt) for r in requests)
    gen = G.make_generate(
        cfg, max_new=gmax, eos_id=eos_id, padded=True, kv_dtype=kv_dtype
    )
    incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
    if warmup:  # compile off the clock, like SlotEngine.warmup
        np.asarray(gen(
            params, jnp.zeros((batch, tp_max), jnp.int32),
            jnp.ones((batch,), jnp.int32), jax.random.key(0),
        ))
    best: ServeStats | None = None
    for _ in range(max(1, trials)):
        results: list[RequestResult] = []
        tick = 0
        i = 0
        t0 = time.perf_counter()
        while i < len(incoming):
            if incoming[i].arrival > tick:
                tick = int(math.ceil(incoming[i].arrival))
            arrived = [r for r in incoming[i:] if r.arrival <= tick]
            wave = arrived[:batch]
            i += len(wave)
            # Fixed (batch, tp_max) shapes: one compile for the whole run.
            prompts = np.zeros((batch, tp_max), np.int32)
            lens = np.ones((batch,), np.int32)  # dummy rows: 1-token prompt
            for row, r in enumerate(wave):
                prompts[row, : len(r.prompt)] = r.prompt
                lens[row] = len(r.prompt)
            out = np.asarray(
                gen(params, jnp.asarray(prompts), jnp.asarray(lens),
                    jax.random.key(0))
            )
            tick += 1 + gmax  # one prefill tick + lockstep decode ticks
            wall = time.perf_counter() - t0
            for row, r in enumerate(wave):
                toks = [int(x) for x in out[row, : r.max_new]]
                if eos_id is not None and eos_id in toks:
                    toks = toks[: toks.index(eos_id) + 1]
                results.append(RequestResult(
                    rid=r.rid, prompt_len=len(r.prompt), tokens=toks,
                    arrival_tick=r.arrival,
                    first_token_tick=tick, finish_tick=tick,
                    first_token_s=wall, finish_s=wall,
                ))
        wall_total = time.perf_counter() - t0
        # Tick arrivals have no live wall analog in a lockstep run (tokens
        # only exist when a wave's batch call returns); convert them at the
        # run's measured seconds-per-tick so wall TTFT compares
        # like-for-like with the engine's live-observed arrivals.
        spt = wall_total / max(tick, 1)
        for res in results:
            res.arrival_s = min(res.arrival_tick * spt, res.first_token_s)
        results.sort(key=lambda r: r.rid)
        stats = ServeStats(
            results=results, ticks=tick, wall_s=wall_total, trace_counts={},
        )
        # Tokens/ticks are deterministic across trials; only wall time is
        # noisy — keep the best-of-N wall, like the bench's _timeit.
        if best is None or stats.wall_s < best.wall_s:
            best = stats
    return best


# ---------------------------------------------------------------------------
# slice-aware slot-pool sizing
# ---------------------------------------------------------------------------


def kv_slot_bytes(
    cfg: TransformerConfig, max_len: int, kv_dtype: str | None = None
) -> int:
    """HBM bytes one slot row pins: K+V across layers at ``max_len``
    positions (+ per-(token, head) scales for int8 caches)."""
    itemsize = 1 if kv_dtype == "int8" else jnp.dtype(cfg.compute_dtype).itemsize
    per = 2 * cfg.n_layers * max_len * cfg.kv_heads * cfg.head_dim * itemsize
    if kv_dtype == "int8":
        per += 2 * cfg.n_layers * max_len * cfg.kv_heads * 4  # f32 scales
    return per


def slots_for_slice(
    slice_bytes: int,
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
) -> int:
    """Slot-pool size a ``slice_bytes`` HBM slice sustains: weights come
    off the top, ``headroom`` covers activations + XLA workspace (the
    plugin's injected cap already shaves 5%, ``parallel/podenv.py``), and
    the rest divides by per-slot KV bytes. 0 means the slice cannot serve
    this config at all — callers must reject, not round up."""
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    usable = slice_bytes * headroom - weight_bytes
    if usable <= 0:
        return 0
    return int(usable // kv_slot_bytes(cfg, max_len, kv_dtype))


def slots_for_gang(
    per_chip_bytes: int,
    n_chips: int,
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
) -> int:
    """Slot-pool size a multi-chip gang sustains, computed over the
    PER-CHIP HBM shares: with the tensor-parallel engine each member chip
    pins ~``weight_bytes / n`` of the model and ``kv_slot_bytes / n`` per
    slot row (kv-heads shard over tp), so the binding constraint is one
    chip's share, not the gang total. When kv-heads do not divide by the
    gang size the cache replicates (``SlotEngine._shard_cache``) and the
    per-chip KV cost is the full row — sized here the same way so the
    estimate can never overshoot what the layout actually pins.
    0 means the gang cannot serve this config — callers reject."""
    if n_chips < 1:
        raise ValueError(f"n_chips must be >= 1, got {n_chips}")
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    per_slot = kv_slot_bytes(cfg, max_len, kv_dtype)
    if n_chips > 1 and cfg.kv_heads % n_chips == 0:
        per_slot_chip = -(-per_slot // n_chips)
        weights_chip = -(-weight_bytes // n_chips)
    else:
        per_slot_chip = per_slot
        weights_chip = weight_bytes
    usable = per_chip_bytes * headroom - weights_chip
    if usable <= 0:
        return 0
    return int(usable // per_slot_chip)


def slots_from_pod_env(
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    env: PodTpuEnv | None = None,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
    unit: MemoryUnit = MemoryUnit.GiB,
) -> int:
    """Slot pool for THIS pod's ``aliyun.com/tpu-mem`` slice, read from
    the plugin-injected env (:class:`~..parallel.podenv.PodTpuEnv`) — the
    closing of the loop: the device plugin carves the slice, the engine
    sizes its admission capacity to it. Multi-chip gangs size over their
    PER-CHIP shares (:func:`slots_for_gang`): the tensor-parallel pool is
    bounded by one member chip's slice, not the gang total. Raises when
    the slice cannot hold even one slot (a misconfigured pod should fail
    loudly at startup, not OOM mid-serve)."""
    pod = env if env is not None else PodTpuEnv.from_env()
    if pod.is_gang:
        # the CONTAINER's portion of the per-chip share: a multi-container
        # gang pod must not have every container size to the pod's whole
        # per-chip slice (they would jointly oversubscribe each chip)
        per_chip_bytes = pod.gang_container_per_chip_bytes(unit)
        n = slots_for_gang(
            per_chip_bytes, len(pod.gang_chips), cfg, max_len,
            weight_bytes=weight_bytes, kv_dtype=kv_dtype, headroom=headroom,
        )
        slice_desc = (
            f"gang slice of {per_chip_bytes / unit.num_bytes:g} "
            f"{unit.value}/chip x {len(pod.gang_chips)} chips"
        )
    else:
        n = slots_for_slice(
            pod.mem_bytes(unit), cfg, max_len,
            weight_bytes=weight_bytes, kv_dtype=kv_dtype, headroom=headroom,
        )
        slice_desc = f"slice of {pod.mem_units_container} {unit.value}"
    if n < 1:
        raise ValueError(
            f"{slice_desc} cannot hold "
            f"weights ({weight_bytes / 2**30:.2f} GiB) plus one "
            f"{max_len}-position KV slot "
            f"({kv_slot_bytes(cfg, max_len, kv_dtype) / 2**30:.3f} GiB) at "
            f"headroom {headroom} — request a larger aliyun.com/tpu-mem "
            "slice, shrink max_len, or quantize (kv_dtype='int8')"
        )
    return n
