"""Continuous-batching serving engine: slot-based KV cache, prefill/decode
interleaving, slice-aware admission.

The static path (``workloads.generate.generate``) is lockstep: every
request in a batch runs all ``max_new`` steps and nothing is admitted
until the whole batch retires — short requests subsidize long ones and a
queued request's TTFT is a full batch lifetime. This engine replaces the
batch with a fixed pool of KV-cache **slots** (``init_slot_cache``):

- **Admission** packs a waiting request into any free slot row via
  :func:`~..workloads.generate.prefill_slot` /
  :func:`~..workloads.generate.extend_slot` — chunked prefill, one fixed
  -width chunk between decode steps, so in-flight slots keep decoding
  while a newcomer's prompt streams in.
- **Decode** advances every occupied slot one token per step through the
  per-slot :func:`~..workloads.generate.decode_step` (vector ``len``);
  a slot that emits EOS (or exhausts its ``max_new``) retires and frees
  its row IMMEDIATELY — the next step can admit into it.
- **Static shapes throughout**: the pool, chunk width, and step batch
  never change shape, so XLA compiles exactly three programs (fresh-slot
  prefill, continuation chunk, decode step) once each; slot churn
  performs zero retraces (``trace_counts``, guarded in tests and the
  serve bench).

Greedy decoding only: the engine's contract is that every request's
tokens are bit-identical to a solo greedy ``generate()`` call — the
property the serving-correctness tests pin, and what makes goodput
comparisons against the static baseline apples-to-apples.

**Clocks.** Arrivals and latencies are tracked on two clocks: wall
seconds, and *ticks* — one tick per model dispatch (a prefill chunk or
one pool-wide decode step). The tick clock is deterministic (no timer
jitter), so the smoke test's continuous-vs-static guards can be exact;
wall numbers are what the bench reports.

**Slice-aware sizing.** :func:`slots_for_slice` derives the slot-pool
size from a pod's ``aliyun.com/tpu-mem`` HBM slice (weights + per-slot
KV bytes + headroom), and :func:`slots_from_pod_env` reads the slice
straight from the plugin-injected container env — the loop back to the
device plugin this repo exists for (``docs/serving.md`` sizing table).
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..const import (
    SLO_TIER_BEST_EFFORT,
    SLO_TIER_CRITICAL,
    WORKLOAD_BEST_EFFORT,
    WORKLOAD_LATENCY_CRITICAL,
    MemoryUnit,
)
from ..parallel.podenv import PodTpuEnv
from ..utils.log import get_logger
from ..utils.metric_catalog import (
    ENGINE_ADAPTER_ENABLED,
    ENGINE_ADAPTER_EVICTIONS_TOTAL,
    ENGINE_ADAPTER_HITS_TOTAL,
    ENGINE_ADAPTER_MISS_STALL_SECONDS,
    ENGINE_ADAPTER_MISSES_TOTAL,
    ENGINE_PREEMPTIONS,
    ENGINE_PREEMPTIONS_TOTAL,
    ENGINE_PREFIX_CACHED_PAGES,
    ENGINE_PREFIX_HIT_RATIO,
    ENGINE_PREFIX_HIT_TOKENS,
    ENGINE_SPEC_ACCEPTANCE_LEN,
    ENGINE_SPEC_ACCEPTED_TOKENS_PER_STEP,
    ENGINE_SPEC_DRAFT_STEPS_TOTAL,
    ENGINE_SPEC_ENABLED,
    ENGINE_SPEC_K,
    ENGINE_SPEC_ROLLBACK_PAGES_TOTAL,
)
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER
from ..workloads import generate as G
from ..workloads.lora import LoraConfig, flatten_lora, lora_flat_len
from ..workloads.transformer import TransformerConfig, shard_params
from .adapters import AdapterCache
from .pages import (
    SCRATCH,
    PageAllocator,
    PagedPlan,
    paged_plan_for_slice,
    pages_for,
    row_span_for,
)
from .drainproto import DrainHandshake
from .profiler import StepProfiler, ceil_rank_quantile
from .radix import RadixCache

log = get_logger("serving.engine")

# SLO tiers (the Tally-style priority split, PAPERS.md 2410.07381):
# latency-critical requests admit first and may preempt best-effort
# victims' pages; best-effort requests absorb the queueing. The names
# live in const so jax-free control-plane code (the daemon's per-tier
# trace-sampling flags) can refer to a tier without importing jax.
TIER_CRITICAL = SLO_TIER_CRITICAL
TIER_BEST_EFFORT = SLO_TIER_BEST_EFFORT
_TIERS = (TIER_CRITICAL, TIER_BEST_EFFORT)
# The AdapterCache speaks workload-class names (it is engine-agnostic);
# the 1:1 tier mapping lives in const's docstring and is pinned here.
_TIER_CLASS = {
    TIER_CRITICAL: WORKLOAD_LATENCY_CRITICAL,
    TIER_BEST_EFFORT: WORKLOAD_BEST_EFFORT,
}


@dataclasses.dataclass(frozen=True)
class Request:
    """One serving request (host-side). ``arrival`` is in engine ticks.

    ``tier`` picks the SLO class (:data:`TIER_CRITICAL` admits ahead of
    :data:`TIER_BEST_EFFORT` and may evict its pages under pressure);
    ``slo_ttft_ticks`` / ``slo_tpot_ticks`` are the tier's latency
    targets on the deterministic tick clock, set by the trace driver and
    scored in :meth:`ServeStats.summary`.

    ``adapter_id`` names the tenant's LoRA fine-tune (the
    ``tpushare.aliyun.com/lora-adapter`` pod annotation, threaded through
    the container env): a :class:`PagedSlotEngine` built with a
    ``lora_store`` pins the adapter's paged weights for the request's
    lifetime and decodes it through the gathered BGMV dispatch — greedy
    tokens bit-identical to ``merge_lora`` + solo ``generate()``. Empty
    means the base model (the null adapter)."""

    rid: int
    prompt: tuple[int, ...]
    max_new: int
    arrival: float = 0.0
    tier: str = TIER_CRITICAL
    slo_ttft_ticks: float | None = None
    slo_tpot_ticks: float | None = None
    adapter_id: str = ""

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new < 1:
            raise ValueError(f"request {self.rid}: max_new must be >= 1")
        if self.tier not in _TIERS:
            raise ValueError(
                f"request {self.rid}: tier {self.tier!r} not in {_TIERS}"
            )


@dataclasses.dataclass
class RequestResult:
    """Per-request outcome + latency telemetry (both clocks)."""

    rid: int
    prompt_len: int
    tokens: list[int]
    arrival_tick: float
    first_token_tick: int = -1
    finish_tick: int = -1
    arrival_s: float = 0.0
    first_token_s: float = 0.0
    finish_s: float = 0.0
    # slot admission (end of queue wait), both clocks
    admit_tick: int = -1
    admit_s: float = 0.0
    # the request's serve trace (utils.tracing), "" when unsampled
    trace_id: str = ""
    # SLO tier + targets (copied from the Request by the paged engine)
    tier: str = TIER_CRITICAL
    slo_ttft_ticks: float | None = None
    slo_tpot_ticks: float | None = None
    # paged-engine telemetry: prompt tokens served from the radix cache,
    # and one dict per preemption ({evict,readmit}_{tick,s}) — a request
    # evicted mid-decode re-prefills its generated tokens on re-admission
    prefix_tokens: int = 0
    preemptions: list[dict] = dataclasses.field(default_factory=list)

    @property
    def ttft_ticks(self) -> float:
        return self.first_token_tick - self.arrival_tick

    @property
    def ttft_s(self) -> float:
        return self.first_token_s - self.arrival_s

    @property
    def tpot_ticks(self) -> float:
        """Ticks per output token after the first (nan for 1-token
        outputs — there is no decode interval to score)."""
        if len(self.tokens) <= 1:
            return float("nan")
        return (self.finish_tick - self.first_token_tick) / (
            len(self.tokens) - 1
        )

    def meets_slo(self) -> bool | None:
        """True/False against the request's tick-clock targets; None when
        the trace driver set none."""
        if self.slo_ttft_ticks is None and self.slo_tpot_ticks is None:
            return None
        if self.slo_ttft_ticks is not None and (
            self.ttft_ticks > self.slo_ttft_ticks
        ):
            return False
        if self.slo_tpot_ticks is not None and len(self.tokens) > 1 and (
            self.tpot_ticks > self.slo_tpot_ticks
        ):
            return False
        return True


@dataclasses.dataclass
class ServeStats:
    """One serving run's results + aggregate metrics."""

    results: list[RequestResult]
    ticks: int
    wall_s: float
    trace_counts: dict[str, int]
    # paged-engine cache telemetry (page occupancy, prefix-hit ratio,
    # preemptions); empty for the contiguous engine / static baseline
    engine_cache: dict = dataclasses.field(default_factory=dict)

    @staticmethod
    def _quantile(vals: list[float], q: float) -> float:
        return ceil_rank_quantile(vals, q)

    def tier_summary(self) -> dict:
        """Per-SLO-tier latency + attainment rows (tick clock: the
        deterministic one the trace driver's targets are set on)."""
        out: dict = {}
        spec_tiers = (
            self.engine_cache.get("speculative") or {}
        ).get("tiers") or {}
        for tier in sorted({r.tier for r in self.results} | set(spec_tiers)):
            rs = [r for r in self.results if r.tier == tier]
            ttft = [r.ttft_ticks for r in rs]
            tpot = [r.tpot_ticks for r in rs if len(r.tokens) > 1]
            scored = [r.meets_slo() for r in rs]
            scored = [s for s in scored if s is not None]
            out[tier] = {
                "requests": len(rs),
                "ttft_p50_ticks": self._quantile(ttft, 0.50),
                "ttft_p99_ticks": self._quantile(ttft, 0.99),
                "tpot_p50_ticks": round(self._quantile(tpot, 0.50), 3)
                if tpot else None,
                "tpot_p99_ticks": round(self._quantile(tpot, 0.99), 3)
                if tpot else None,
                "preemptions": sum(len(r.preemptions) for r in rs),
                "slo_attainment": round(sum(scored) / len(scored), 3)
                if scored else None,
            }
            if tier in spec_tiers:
                # Accepted-vs-proposed speculation breakdown per tier:
                # which SLO class the draft model's lookahead is
                # actually paying off for.
                out[tier]["spec_proposed"] = spec_tiers[tier]["proposed"]
                out[tier]["spec_accepted"] = spec_tiers[tier]["accepted"]
        return out

    def summary(self) -> dict:
        """Flat metrics dict (the serve bench's report row)."""
        tokens = sum(len(r.tokens) for r in self.results)
        ttft_t = [r.ttft_ticks for r in self.results]
        ttft_s = [r.ttft_s for r in self.results]
        out = {
            "requests": len(self.results),
            "tokens": tokens,
            "ticks": self.ticks,
            "wall_s": round(self.wall_s, 4),
            # Goodput: completed requests' generated tokens over makespan
            # (post-EOS padding never exists here — retirement is
            # immediate — so every counted token is useful).
            "goodput_tokens_per_s": round(tokens / self.wall_s, 1)
            if self.wall_s > 0 else None,
            "goodput_tokens_per_tick": round(tokens / max(self.ticks, 1), 3),
            "ttft_p50_ticks": self._quantile(ttft_t, 0.50),
            "ttft_p99_ticks": self._quantile(ttft_t, 0.99),
            "ttft_p50_ms": round(self._quantile(ttft_s, 0.50) * 1e3, 2),
            "ttft_p99_ms": round(self._quantile(ttft_s, 0.99) * 1e3, 2),
            "trace_counts": dict(self.trace_counts),
        }
        if any(
            r.tier != TIER_CRITICAL or r.meets_slo() is not None
            for r in self.results
        ) or (self.engine_cache.get("speculative") or {}).get("tiers"):
            out["tiers"] = self.tier_summary()
        if self.engine_cache:
            out["cache"] = dict(self.engine_cache)
        return out


@dataclasses.dataclass
class _Slot:
    state: str = "free"  # free | prefill | decode
    req: Request | None = None
    done: int = 0  # prompt tokens prefilled so far
    last: int = 0  # last sampled token (decode input)
    result: RequestResult | None = None


class SlotEngine:
    """Continuous-batching engine over ``slots`` KV-cache rows.

    ``prefill_chunk`` is the static prompt-chunk width (admission cost
    granularity); ``max_len`` bounds each slot row (prompt + generated).
    Admission is slice-aware up front: a request whose
    ``prompt + max_new`` cannot fit a slot row is rejected at submit
    time instead of overflowing mid-decode.
    """

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        *,
        slots: int,
        max_len: int,
        prefill_chunk: int = 64,
        eos_id: int | None = None,
        kv_dtype: str | None = None,
        mesh=None,
        metrics_pod: str = "",
        slo_budget=None,
        governor=None,
        profiler_capacity: int = 1024,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if max_len > cfg.max_seq:
            raise ValueError(
                f"max_len {max_len} exceeds cfg.max_seq {cfg.max_seq} "
                "(RoPE table bound)"
            )
        if prefill_chunk > max_len:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} exceeds the slot row "
                f"({max_len} positions) — even one chunk cannot be packed"
            )
        self.params = params
        self.cfg = cfg
        self.n_slots = slots
        self.max_len = max_len
        self.chunk = prefill_chunk
        self.eos_id = eos_id
        self.kv_dtype = kv_dtype
        self.cache = self._make_cache(kv_dtype)
        # Tensor-parallel serving across a granted gang: with a mesh (from
        # ``parallel.podenv.gang_mesh`` inside a multi-chip grant), the
        # model weights shard per ``transformer.param_specs`` (heads /
        # mlp-hidden / vocab over tp) and the slot-pool KV cache shards
        # its kv-heads dimension over the same axis — every chip of the
        # gang holds 1/tp of the weights and 1/tp of every slot row, and
        # XLA inserts the psums over the gang's ICI sub-slice (the
        # NamedSharding/GSPMD pattern; nothing here hand-schedules
        # communication). The engine's host loop, static shapes, and
        # compile-count guarantees are unchanged: sharding is a layout
        # property of the same three programs.
        self.mesh = mesh if mesh is not None and mesh.shape.get("tp", 1) > 1 else None
        if self.mesh is not None:
            self.params = shard_params(self.params, self.mesh, cfg)
            self.cache = self._shard_cache(self.cache)
        self.ticks = 0
        # One entry per compiled program; a counting wrapper increments at
        # TRACE time, so steady-state slot churn must leave these frozen
        # (the no-retrace guard the tests and serve bench assert).
        self.trace_counts = {"prefill": 0, "extend": 0, "decode": 0}
        # Interference observability plane (docs/observability.md):
        # per-decode-step wall-time profiler (always on — one ring write
        # per pool-wide step), an optional SLO error budget fed at retire
        # (utils/slo.py), and an optional best-effort step governor
        # consulted before each decode dispatch (serving/governor.py).
        self.metrics_pod = metrics_pod
        self.profiler = StepProfiler(capacity=profiler_capacity)
        self.slo_budget = slo_budget
        self.governor = governor
        self._warming = False
        # Build identity on the engine's /metrics too (idempotent gauge;
        # the inspect header reads it off any scraped endpoint).
        from ..utils.metrics import publish_build_info

        publish_build_info(component="engine")
        self._build_fns()

    def _make_cache(self, kv_dtype: str | None):
        """The KV layout this engine runs on — :class:`PagedSlotEngine`
        overrides with the paged buffers; called from ``__init__`` before
        any sharding/compilation."""
        return G.init_slot_cache(
            self.cfg, self.n_slots, self.max_len, kv_dtype=kv_dtype
        )

    def _shard_cache(self, cache, cfg: TransformerConfig | None = None):
        """Place the slot-pool cache tensor-parallel: K/V (and int8
        scales) shard their kv-heads axis over tp — each gang chip pins
        ``kv_slot_bytes / tp`` per row, which is what lets a gang's
        per-chip HBM share hold a pool no single chip could
        (:func:`slots_for_gang`). A kv-head count tp does not divide
        falls back to replication for that buffer (the
        ``prune_unshardable`` rule), keeping correctness over memory.
        ``cfg`` overrides whose kv-head count is checked (the paged
        engine's draft-model pool shards by the DRAFT config)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = self.mesh.shape["tp"]
        divisible = (cfg or self.cfg).kv_heads % tp == 0

        def spec_for(name: str, ndim: int):
            if name == "len" or not divisible:
                return P()
            # k/v: [L, slots, max_len, Hkv, Dh]; scales: [L, slots, max_len, Hkv]
            parts = [None] * ndim
            parts[3] = "tp"
            return P(*parts)

        return {
            name: jax.device_put(
                val, NamedSharding(self.mesh, spec_for(name, val.ndim))
            )
            for name, val in cache.items()
        }

    def _build_fns(self) -> None:
        cfg = self.cfg

        def prefill_fn(params, tokens, cache, slot, n_real):
            self.trace_counts["prefill"] += 1
            logits, cache = G.prefill_slot(
                params, tokens, cache, cfg, slot=slot, n_real=n_real
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

        def extend_fn(params, tokens, cache, slot, n_real):
            self.trace_counts["extend"] += 1
            logits, cache = G.extend_slot(
                params, tokens, cache, cfg, slot=slot, n_real=n_real
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

        def decode_fn(params, tokens, cache, active):
            self.trace_counts["decode"] += 1
            logits, new = G.decode_step(params, tokens, cache, cfg)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            # Idle rows (free slots, mid-prefill slots) must not advance:
            # freeze their lengths so the next chunk/decode write lands
            # where the slot's real content ends.
            new = {**new, "len": jnp.where(active, new["len"], cache["len"])}
            return nxt, new

        # Caches are donated: the engine holds the only reference, and a
        # slot pool big enough to matter should not be double-buffered in
        # HBM on every step.
        self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))
        self._extend = jax.jit(extend_fn, donate_argnums=(2,))
        self._decode = jax.jit(decode_fn, donate_argnums=(2,))

    def warmup(self) -> None:
        """Compile all three programs off the clock (fresh-slot prefill,
        continuation chunk, decode step) so a timed :meth:`run` starts
        warm — the serving analog of the bench's warmup iterations. Slot
        0's row is scribbled on, which is safe by the visibility
        invariant; the tick clock is reset afterwards."""
        # chunk + 1 tokens forces the continuation (extend) trace too,
        # when the pool is big enough to ever admit a multi-chunk prompt
        # (same footprint rule as validate).
        plen = self.chunk + 1
        if max(2 * self.chunk, plen + 2) > self.max_len:
            plen = min(self.chunk, self.max_len - 2)
        self._warming = True
        try:
            self.run([Request(rid=-1, prompt=tuple(range(1, plen + 1)),
                              max_new=2, arrival=0.0)])
        finally:
            self._warming = False
        self.ticks = 0
        # compile-time decode steps must not pollute the steady-state
        # step-profile window (or the exported histogram — _warming above
        # suppressed the flush)
        self.profiler.reset()

    def validate(self, req: Request) -> None:
        # Every prefill write is a FULL chunk (static width; the pad tail
        # is invisible), so the prompt's footprint is its chunk-padded
        # length: a final chunk that straddled the row end would make
        # dynamic_update_slice clamp the write start BACKWARDS over
        # already-cached positions — silent KV corruption, not an error.
        plen = len(req.prompt)
        padded = -(-plen // self.chunk) * self.chunk
        need = max(padded, plen + req.max_new)
        if need > self.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {plen} (chunk-padded {padded}) "
                f"+ max_new {req.max_new} needs {need} positions, exceeding "
                f"the slot row ({self.max_len}) — size the pool for the "
                "workload or reject upstream (slice-aware admission)"
            )

    def _chunk_arrays(self, req: Request, done: int) -> tuple[jax.Array, int]:
        real = req.prompt[done : done + self.chunk]
        buf = np.zeros((self.chunk,), np.int32)
        buf[: len(real)] = real
        return jnp.asarray(buf), len(real)

    def _note_slo(self, res: RequestResult) -> None:
        """Feed the retired request's SLO verdict into the attached error
        budget (``utils/slo.py``); requests without targets (and the
        warmup synthetic) record nothing."""
        if self.slo_budget is None or res.rid < 0:
            return
        ok = res.meets_slo()
        if ok is not None:
            self.slo_budget.record(res.tier, ok)

    def _flush_step_profile(self) -> None:
        """Batch-export the step profile (histogram + rolling p50/p99
        gauges) — once per run, never per step; suppressed during warmup
        so compile-time steps never reach ``/metrics``."""
        if not self._warming:
            self.profiler.flush(REGISTRY, pod=self.metrics_pod)

    def _record_request_trace(self, res: RequestResult, base_ns: int) -> None:
        """Emit the request's span timeline (queue wait -> prefill chunks
        -> decode steps -> retire) into the process trace store.

        Reconstructed from the timestamps the engine already collects, at
        retire time only — the per-token hot loop pays zero tracing cost
        and the compile-count/bit-identity guarantees are untouched.
        Unsampled requests (``TRACER.sample_ratio``) record nothing; the
        warmup's synthetic request (rid < 0) is skipped."""
        if res.rid < 0:
            return

        def at(seconds: float) -> int:
            return base_ns + int(seconds * 1e9)

        attrs = {
            "rid": res.rid,
            "prompt_len": res.prompt_len,
            "tokens": len(res.tokens),
            "ttft_ticks": res.ttft_ticks,
            "slots": self.n_slots,
            "tier": res.tier,
        }
        if res.prefix_tokens:
            attrs["prefix_tokens"] = res.prefix_tokens
        # per-tier root sampling (--trace-sample-critical /
        # --trace-sample-besteffort): best-effort churn can be
        # down-sampled without losing critical-tier traces
        ctx = TRACER.record_span(
            "serve.request", at(res.arrival_s), at(res.finish_s),
            attributes=attrs, tier=res.tier,
        )
        if ctx is None:
            return
        res.trace_id = ctx.trace_id
        admit = res.admit_s if res.admit_tick >= 0 else res.arrival_s
        TRACER.record_span(
            "serve.queue", at(res.arrival_s), at(admit), parent=ctx,
            attributes={"wait_ticks": max(0, res.admit_tick - res.arrival_tick)},
        )
        chunks = -(-res.prompt_len // self.chunk)
        TRACER.record_span(
            "serve.prefill", at(admit), at(res.first_token_s), parent=ctx,
            attributes={"chunks": chunks, "chunk_width": self.chunk},
        )
        TRACER.record_span(
            "serve.decode", at(res.first_token_s), at(res.finish_s),
            parent=ctx,
            attributes={"decode_steps": max(0, len(res.tokens) - 1)},
        )
        TRACER.record_span(
            "serve.retire", at(res.finish_s), at(res.finish_s), parent=ctx,
            attributes={"finish_tick": res.finish_tick},
        )
        for pre in res.preemptions:
            # one span per eviction: evict -> re-admission (or finish,
            # for a request still preempted when the run drained)
            TRACER.record_span(
                "serve.preempt",
                at(pre["evict_s"]),
                at(pre.get("readmit_s", res.finish_s)),
                parent=ctx,
                attributes={
                    "evict_tick": pre["evict_tick"],
                    "readmit_tick": pre.get("readmit_tick", -1),
                    "tier": res.tier,
                },
            )

    def run(self, requests: Sequence[Request]) -> ServeStats:
        """Serve ``requests`` to completion; returns results + metrics.

        The loop per iteration: (1) move arrived requests to the pending
        queue, (2) admit pending requests into free slots, (3) run ONE
        prompt chunk for the oldest mid-prefill slot (chunked prefill —
        bounded interference with decoding neighbors), (4) run one decode
        step across all decoding slots. Each model dispatch advances the
        tick clock by one.
        """
        for r in requests:
            self.validate(r)
        self.ticks = 0  # arrivals are relative to this run's start
        incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
        slots = [_Slot() for _ in range(self.n_slots)]
        pending: deque[Request] = deque()
        results: list[RequestResult] = []
        live: dict[int, RequestResult] = {}
        i = 0
        t0 = time.perf_counter()
        base_ns = time.time_ns()  # wall anchor for the request spans

        def now() -> float:
            return time.perf_counter() - t0

        def retire(idx: int) -> None:
            s = slots[idx]
            s.result.finish_tick = self.ticks
            s.result.finish_s = now()
            results.append(s.result)
            self._record_request_trace(s.result, base_ns)
            self._note_slo(s.result)
            slots[idx] = _Slot()

        while i < len(incoming) or pending or any(
            s.state != "free" for s in slots
        ):
            while i < len(incoming) and incoming[i].arrival <= self.ticks:
                req = incoming[i]
                live[req.rid] = RequestResult(
                    rid=req.rid, prompt_len=len(req.prompt), tokens=[],
                    arrival_tick=req.arrival, arrival_s=now(),
                )
                pending.append(req)
                i += 1
            busy = any(s.state != "free" for s in slots)
            if not busy and not pending:
                # Pool idle, nothing queued: jump the tick clock to the
                # next arrival instead of spinning.
                self.ticks = max(self.ticks, int(math.ceil(incoming[i].arrival)))
                continue

            for idx, s in enumerate(slots):
                if s.state == "free" and pending:
                    req = pending.popleft()
                    res = live[req.rid]
                    res.admit_tick = self.ticks
                    res.admit_s = now()
                    slots[idx] = _Slot(
                        state="prefill", req=req, done=0, result=res
                    )

            pre = [idx for idx, s in enumerate(slots) if s.state == "prefill"]
            if pre:
                idx = min(pre, key=lambda j: slots[j].result.arrival_tick)
                s = slots[idx]
                tokens, n_real = self._chunk_arrays(s.req, s.done)
                if self.governor is not None:
                    # prefill chunks are model dispatches too: an
                    # ungoverned prefill burst would leak the very
                    # contention the decode throttle exists to stop
                    self.governor.before_step()
                fn = self._prefill if s.done == 0 else self._extend
                tok, self.cache = fn(
                    self.params, tokens, self.cache,
                    np.int32(idx), np.int32(n_real),
                )
                self.ticks += 1
                s.done += n_real
                if s.done == len(s.req.prompt):
                    first = int(tok)
                    s.result.first_token_tick = self.ticks
                    s.result.first_token_s = now()
                    s.result.tokens.append(first)
                    if (
                        self.eos_id is not None and first == self.eos_id
                    ) or s.req.max_new == 1:
                        retire(idx)
                    else:
                        s.state = "decode"
                        s.last = first

            dec = [idx for idx, s in enumerate(slots) if s.state == "decode"]
            if dec:
                toks = np.zeros((self.n_slots,), np.int32)
                active = np.zeros((self.n_slots,), bool)
                for idx in dec:
                    toks[idx] = slots[idx].last
                    active[idx] = True
                if self.governor is not None:
                    # best-effort pacing (Tally-style): may sleep, never
                    # skips or reorders the dispatch — outside the timed
                    # step so throttling isn't misread as contention
                    self.governor.before_step()
                _step_t0 = time.perf_counter()
                nxt, self.cache = self._decode(
                    self.params, jnp.asarray(toks), self.cache,
                    jnp.asarray(active),
                )
                self.ticks += 1
                nxt = np.asarray(nxt)  # forces the step's device work
                self.profiler.record(time.perf_counter() - _step_t0)
                for idx in dec:
                    s = slots[idx]
                    t = int(nxt[idx])
                    s.result.tokens.append(t)
                    s.last = t
                    if (
                        self.eos_id is not None and t == self.eos_id
                    ) or len(s.result.tokens) >= s.req.max_new:
                        retire(idx)

        results.sort(key=lambda r: r.rid)
        self._flush_step_profile()
        return ServeStats(
            results=results, ticks=self.ticks,
            wall_s=time.perf_counter() - t0,
            trace_counts=dict(self.trace_counts),
        )


# ---------------------------------------------------------------------------
# paged engine: page-table KV + radix prefix cache + SLO-tiered admission
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _PagedSlot:
    state: str = "free"  # free | prefill | decode
    req: Request | None = None
    # effective prompt: original prompt + tokens regenerated after a
    # preemption (re-admission re-prefills them — bit-identical by the
    # chunked-verification math extend_slot is built on)
    prompt: tuple[int, ...] = ()
    done: int = 0  # prompt tokens materialized in the row (incl. prefix hits)
    pos: int = 0  # logical row length (host mirror of len[slot])
    last: int = 0
    result: RequestResult | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    shared: int = 0  # leading pages matched from the radix tree (read-only)
    table: np.ndarray | None = None  # [row_pages] int32 physical page ids
    # [pages_per_adapter] int32 adapter-slab page ids (None when the
    # engine serves no LoRA store; all-SCRATCH = the null adapter — slab
    # row 0 is permanently zero, so the gathered delta is exactly zero)
    atable: np.ndarray | None = None
    # True when the row's draft-pool KV is not trustworthy (handoff
    # import seeds carry only target KV): the row plain-decodes forever
    # and retire() must not adopt its pages into the radix tree, where a
    # future prefix match would speculate over garbage draft state.
    draft_stale: bool = False


class PagedSlotEngine(SlotEngine):
    """:class:`SlotEngine` over **paged** KV: rows read and write through
    per-request page tables (``serving/pages.py``) instead of owning a
    contiguous ``max_len`` strip, so a request pins only the pages its
    tokens occupy — the ParvaGPU-style spatial sharing of one
    ``aliyun.com/tpu-mem`` slice. On top of the allocator:

    - a **radix prefix cache** (``serving/radix.py``): requests sharing a
      system prompt prefill it once and branch by reference-counted
      pages (``radix=False`` disables);
    - **SLO-tiered admission**: :data:`TIER_CRITICAL` requests admit
      ahead of :data:`TIER_BEST_EFFORT` and, under page pressure, evict
      radix pages and then preempt best-effort victims (whose requests
      re-queue and re-prefill on re-admission).

    Correctness bar unchanged from the contiguous engine: greedy tokens
    BIT-IDENTICAL to solo ``generate()`` — the paged kernels gather each
    row's pages into exactly the contiguous logical layout before
    running the shared ``decode_block`` — with zero retraces across
    churn, preemption included (page tables are data, not shapes).

    Geometry: ``prefill_chunk`` must be a page-size multiple (radix
    matches floor to chunk boundaries, so shared pages always cover
    whole chunks) and ``total_pages`` must cover one ``max_len`` row
    (the progress guarantee: a lone request can always finish after the
    pool drains around it).

    **Speculative decoding** (``draft_params``/``draft_cfg``): a small
    draft model proposes ``spec_k`` tokens per decoding row per round;
    the target verifies the whole proposal in ONE forward
    (``paged_verify_block``) and greedy accept/rollback keeps emitted
    tokens bit-identical to the plain engine — the verify argmax IS the
    sequential decode stream. Draft KV lives as parallel paged tensors
    indexed by the SAME page ids/tables out of the same refcounted
    allocator, so one page's cost is target + draft slot bytes
    (:func:`~.pages.paged_plan_for_slice` charges both against the
    slice budget). Lookahead pages come from plain ``allocator.alloc``
    — drafts sit BELOW adapters and KV in the eviction ladder and never
    evict radix pages or preempt rows; on rejection the tail pages roll
    back by refcount release. Per-row acceptance lengths are data, not
    shapes: exactly five compiled programs (prefill, extend, decode,
    draft, verify), zero retraces across churn.
    """

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        *,
        slots: int,
        max_len: int,
        total_pages: int,
        page_size: int,
        prefill_chunk: int = 64,
        eos_id: int | None = None,
        kv_dtype: str | None = None,
        mesh=None,
        radix: bool = True,
        metrics_pod: str = "",
        slo_budget=None,
        governor=None,
        profiler_capacity: int = 1024,
        draft_params=None,
        draft_cfg: TransformerConfig | None = None,
        spec_k: int = 4,
        lora_store: dict | None = None,
        lora_cfg: LoraConfig | None = None,
    ):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if (lora_store is None) != (lora_cfg is None):
            raise ValueError(
                "lora_store and lora_cfg enable multi-LoRA serving "
                "together — passing one without the other is a config bug"
            )
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "draft_params and draft_cfg enable speculative decoding "
                "together — passing one without the other is a config bug"
            )
        if draft_cfg is not None:
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab} != target vocab "
                    f"{cfg.vocab} — draft proposals could never be compared "
                    "token-for-token against target greedy picks"
                )
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if prefill_chunk % page_size != 0:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be a multiple of "
                f"page_size {page_size} (radix matches floor to chunk "
                "boundaries, so shared pages must cover whole chunks)"
            )
        if total_pages < pages_for(max_len, page_size):
            raise ValueError(
                f"total_pages {total_pages} cannot cover one {max_len}"
                f"-position row ({pages_for(max_len, page_size)} pages of "
                f"{page_size}) — even a lone request could deadlock; size "
                "the pool with paged_plan_for_slice"
            )
        self.page_size = page_size
        self.total_pages = total_pages
        # The page table spans max_len rounded UP to a chunk multiple:
        # the final chunk's static-width pad tail scatters through table
        # entries (landing on SCRATCH), and a narrower table would let
        # JAX's index clamping fold those writes into the last REAL page.
        # row_span_for keeps this width and the sizing math's in lockstep.
        self.row_pages = row_span_for(max_len, prefill_chunk) // page_size
        # Speculative-decoding state must exist BEFORE super().__init__:
        # the overridden _build_fns (called from there) shapes its
        # programs on whether a draft model rides along.
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec_k = int(spec_k)
        # Multi-LoRA state likewise: _build_fns threads the adapter-slab
        # gather through every target program when a store is attached
        # (ALWAYS — adapter identity is page-table data, so a batch
        # mixing 100 tenants and the base model is still one dispatch).
        self.lora_store = lora_store
        self.lora_cfg = lora_cfg
        if lora_cfg is not None:
            # one slab row per pool page: [page_size * d_model] f32 —
            # the flat adapter vector (workloads/lora.py layout) stripes
            # across ceil(len / row) pages of the SAME allocator id space
            self._adapter_page_floats = page_size * cfg.d_model
            self.pages_per_adapter = max(1, -(
                -lora_flat_len(cfg, lora_cfg) // self._adapter_page_floats
            ))
        # escape hatch: True parks every row on the plain decode path
        # (tests pin that a suspended spec engine is bitwise the plain
        # engine; both paths are compiled by warmup either way)
        self._spec_suspended = False
        super().__init__(
            params, cfg, slots=slots, max_len=max_len,
            prefill_chunk=prefill_chunk, eos_id=eos_id, kv_dtype=kv_dtype,
            mesh=mesh, metrics_pod=metrics_pod, slo_budget=slo_budget,
            governor=governor, profiler_capacity=profiler_capacity,
        )
        self.allocator = PageAllocator(total_pages)
        self.radix = RadixCache(page_size, self.allocator) if radix else None
        self.preemptions = 0
        # Paged LoRA adapters (serving/adapters.py): per-tenant low-rank
        # weights live as flat f32 vectors striped across pages of the
        # SAME refcounted pool as KV and draft KV — the slab's +1 row 0
        # is the scratch/null adapter and stays all-zero forever, so a
        # base-model row's gathered delta is exactly zero. The slab is a
        # device buffer indexed by per-slot adapter page tables at
        # decode; the AdapterCache is the host residency ledger.
        if lora_cfg is not None:
            self.adapters = AdapterCache(
                self.allocator, self.pages_per_adapter
            )
            self._lora_slab = jnp.zeros(
                (total_pages + 1, self._adapter_page_floats), jnp.float32
            )
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                # shard the flat-feature axis over tp when it divides —
                # the same condition paged_plan_for_slice charges the
                # sharded per-chip adapter page bytes under
                spec = (
                    P(None, "tp")
                    if cfg.d_model % self.mesh.shape["tp"] == 0 else P()
                )
                self._lora_slab = jax.device_put(
                    self._lora_slab, NamedSharding(self.mesh, spec)
                )
            # published-counter watermarks + per-load stall seconds
            # (flushed once per run, the _spec_pub pattern)
            self._adapter_pub = {"hits": 0, "misses": 0, "evictions": 0}
            self._adapter_stalls: list[float] = []
            # rid -> perf_counter of the first head-blocked acquire, so
            # the eventual landing charges the whole wait as stall
            self._adapter_waits: dict[int, float] = {}
        else:
            self.adapters = None
        # Draft-model KV: a parallel paged pool indexed by the SAME page
        # ids and per-row tables as the target's — one allocator, one
        # refcount table, so a page's slice cost is target + draft bytes
        # (PagedPlan.draft_bytes) and releasing a page frees both models'
        # stale KV at once. Radix-shared pages carry valid draft KV too:
        # the combined prefill writes both pools in one dispatch.
        if draft_params is not None:
            self.trace_counts.update({"draft": 0, "verify": 0})
            self.draft_cache = G.init_paged_cache(
                draft_cfg, slots, total_pages + 1, page_size,
                kv_dtype=kv_dtype,
            )
            if self.mesh is not None:
                self.draft_params = shard_params(
                    draft_params, self.mesh, draft_cfg
                )
                self.draft_cache = self._shard_cache(
                    self.draft_cache, draft_cfg
                )
            self._spec_draft_steps = 0
            self._spec_rollback_pages = 0
            # published-counter watermarks: publish_metrics exports
            # counter DELTAS, so back-to-back runs never double-count
            self._spec_pub = {"draft_steps": 0, "rollback": 0}
            # histogram accumulators, value -> multiplicity: bounded by
            # the k+1 distinct acceptance lengths (and slots*(k+1)
            # distinct per-round totals), flushed once per run — never
            # a per-step registry call
            self._spec_accept_hist: dict[int, int] = {}
            self._spec_step_hist: dict[int, int] = {}
            self._spec_tiers: dict[str, dict[str, int]] = {}
            self._spec_lookahead_high = 0
        else:
            self.draft_cache = None
        # Live-defragmentation drain (allocator/defrag.py move protocol):
        # request_drain() quiesces the current run() at its next iteration
        # boundary — in-flight requests are captured into a JSON-safe
        # snapshot, their pages freed — and restore_snapshot() re-admits
        # them on another engine (the destination slice) bit-identically.
        # The arm/capture/consume state machine lives in
        # serving/drainproto.py (jax-free, so tools/tpumc can enumerate
        # every ordering of it against a simulated serving loop).
        self._drain = DrainHandshake()
        self._restore_tokens: dict[int, tuple[int, ...]] = {}
        # snapshot_ids this instance already restored: the move
        # protocol's restore delivery is at-least-once across the
        # resume/commit crash window, so the receiver deduplicates. Keyed
        # on the mover-stamped identity, NOT content — two independent
        # moves of a deterministic workload can legitimately carry
        # byte-identical snapshots, and both must serve.
        self._restored_ids: deque[str] = deque(maxlen=16)
        # Disaggregated prefill/decode serving (serving/handoff.py). On a
        # PREFILL-tier engine, a request that finishes its prompt with
        # decode work left is exported (row + KV page bytes) to the sink
        # instead of decoding here. On a DECODE-tier engine,
        # _import_seeds maps rid -> a staged KV import (pages already
        # owned by this engine's allocator, bytes already written by
        # import_kv_pages); the admission loop adopts the seed straight
        # into decode state, skipping prefill entirely.
        self._handoff_sink = None
        self._import_seeds: dict[int, dict] = {}

    def _make_cache(self, kv_dtype: str | None):
        # +1: physical page 0 is the scratch write sink (pages.SCRATCH)
        return G.init_paged_cache(
            self.cfg, self.n_slots, self.total_pages + 1, self.page_size,
            kv_dtype=kv_dtype,
        )

    def _build_fns(self) -> None:
        cfg = self.cfg
        lcfg = self.lora_cfg

        def lora_kw(lw: tuple) -> dict:
            # LoRA threading: when the engine carries an adapter store,
            # every TARGET program takes two trailing args — the device
            # slab and the batch's adapter page tables — and gathers
            # per-slot low-rank views inside the jit (one dispatch no
            # matter how many distinct adapters the batch mixes; adapter
            # identity is table DATA, never a shape). Draft programs
            # never take them: proposals are guesses the target verifies,
            # and the verify/decode argmax carries the adapter.
            if not lw:
                return {}
            slab, atab = lw
            return {
                "lora": G.lora_bgmv_views(slab, atab, cfg, lcfg),
                "lora_scale": lcfg.scale,
            }

        if self.draft_params is None:

            def prefill_fn(params, tokens, cache, slot, table, n_real, *lw):
                self.trace_counts["prefill"] += 1
                logits, cache = G.paged_prefill_slot(
                    params, tokens, cache, cfg, slot=slot, page_table=table,
                    n_real=n_real, **lora_kw(lw),
                )
                return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

            def extend_fn(params, tokens, cache, slot, table, pos, n_real,
                          *lw):
                self.trace_counts["extend"] += 1
                logits, cache = G.paged_extend_slot(
                    params, tokens, cache, cfg, slot=slot, page_table=table,
                    pos=pos, n_real=n_real, **lora_kw(lw),
                )
                return jnp.argmax(logits[0], -1).astype(jnp.int32), cache

            def decode_fn(params, tokens, cache, tables, active, *lw):
                self.trace_counts["decode"] += 1
                logits, new = G.paged_decode_step(
                    params, tokens, cache, cfg, page_tables=tables,
                    **lora_kw(lw),
                )
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                new = {
                    **new, "len": jnp.where(active, new["len"], cache["len"]),
                }
                return nxt, new

            self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))
            self._extend = jax.jit(extend_fn, donate_argnums=(2,))
            self._decode = jax.jit(decode_fn, donate_argnums=(2,))
            return

        # Speculative mode: every program that materializes KV runs the
        # draft model IN THE SAME DISPATCH (same chunk, same page table),
        # so the draft pool never falls out of lockstep with the target —
        # through governor throttling, page pressure, preemption churn —
        # with zero extra dispatches and the target subgraph (and so its
        # argmax tokens) unchanged. The two spec-only programs are the
        # draft lookahead scan and the one-forward verify; acceptance
        # lengths flow through them as DATA, so the compiled-program
        # count stays at five regardless of what gets accepted.
        dcfg = self.draft_cfg
        k = self.spec_k

        def prefill_fn(params, dparams, tokens, cache, dcache, slot, table,
                       n_real, *lw):
            self.trace_counts["prefill"] += 1
            logits, cache = G.paged_prefill_slot(
                params, tokens, cache, cfg, slot=slot, page_table=table,
                n_real=n_real, **lora_kw(lw),
            )
            _, dcache = G.paged_prefill_slot(
                dparams, tokens, dcache, dcfg, slot=slot, page_table=table,
                n_real=n_real,
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache, dcache

        def extend_fn(params, dparams, tokens, cache, dcache, slot, table,
                      pos, n_real, *lw):
            self.trace_counts["extend"] += 1
            logits, cache = G.paged_extend_slot(
                params, tokens, cache, cfg, slot=slot, page_table=table,
                pos=pos, n_real=n_real, **lora_kw(lw),
            )
            _, dcache = G.paged_extend_slot(
                dparams, tokens, dcache, dcfg, slot=slot, page_table=table,
                pos=pos, n_real=n_real,
            )
            return jnp.argmax(logits[0], -1).astype(jnp.int32), cache, dcache

        def decode_fn(params, dparams, tokens, cache, dcache, tables, active,
                      *lw):
            self.trace_counts["decode"] += 1
            logits, new = G.paged_decode_step(
                params, tokens, cache, cfg, page_tables=tables,
                **lora_kw(lw),
            )
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            new = {**new, "len": jnp.where(active, new["len"], cache["len"])}
            _, dnew = G.paged_decode_step(
                dparams, tokens, dcache, dcfg, page_tables=tables
            )
            dnew = {
                **dnew, "len": jnp.where(active, dnew["len"], dcache["len"]),
            }
            return nxt, new, dnew

        def draft_fn(dparams, tokens, dcache, tables, active):
            self.trace_counts["draft"] += 1
            lens0 = dcache["len"]

            def step(carry, _):
                tok, c = carry
                logits, c = G.paged_decode_step(
                    dparams, tok, c, dcfg, page_tables=tables
                )
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return (nxt, c), nxt

            # k+1 steps for k proposals: the extra step writes the last
            # proposal's OWN KV entry — an unwritten (zero) entry there
            # would silently poison every later draft prediction once
            # that token is accepted.
            (_, dcache), props = jax.lax.scan(
                step, (tokens, dcache), None, length=k + 1
            )
            drafts = jnp.transpose(props[:k])  # [k+1, B] -> [B, k]
            dcache = {
                **dcache,
                "len": jnp.where(active, lens0 + k + 1, lens0),
            }
            return drafts, dcache

        def verify_fn(params, block, cache, dcache, tables, active, *lw):
            self.trace_counts["verify"] += 1
            pos0 = cache["len"]
            dlen0 = dcache["len"]
            logits, new = G.paged_verify_block(
                params, block, cache, cfg, page_tables=tables, **lora_kw(lw),
            )
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)  # [B, k+1]
            # greedy accept: the longest draft prefix matching the
            # target's own picks; everything after position `a` is
            # rejected and its KV rewound past by the new lengths
            match = (block[:, 1:] == greedy[:, :k]).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B] in [0, k]
            new_len = jnp.where(active, pos0 + a + 1, pos0)
            new = {**new, "len": new_len}
            dcache = {**dcache, "len": jnp.where(active, pos0 + a + 1, dlen0)}
            return greedy, a, new, dcache

        self._prefill = jax.jit(prefill_fn, donate_argnums=(3, 4))
        self._extend = jax.jit(extend_fn, donate_argnums=(3, 4))
        self._decode = jax.jit(decode_fn, donate_argnums=(3, 4))
        self._draft = jax.jit(draft_fn, donate_argnums=(2,))
        self._verify = jax.jit(verify_fn, donate_argnums=(2, 3))

    # --- multi-LoRA adapters (serving/adapters.py) ------------------------

    def validate(self, req: Request) -> None:
        super().validate(req)
        if req.adapter_id:
            if self.lora_cfg is None:
                raise ValueError(
                    f"request {req.rid}: adapter {req.adapter_id!r} on an "
                    "engine with no lora_store — the router sent a tenant "
                    "request to a base-model replica"
                )
            if req.adapter_id not in self.lora_store:
                raise ValueError(
                    f"request {req.rid}: unknown adapter "
                    f"{req.adapter_id!r} — not in this engine's lora_store"
                )

    def _write_adapter_pages(
        self, adapter_id: str, pages: list[int]
    ) -> None:
        """Stripe the adapter's flat vector (``workloads/lora.py``
        layout, zero-padded to whole slab rows) into freshly-allocated
        slab pages — the device half of an :class:`AdapterCache` miss.
        One eager batched scatter with the adapters lock released, off
        the jit'd hot path: zero retraces."""
        flat = np.asarray(
            flatten_lora(
                self.lora_store[adapter_id], self.cfg, self.lora_cfg
            ),
            np.float32,
        )
        buf = np.zeros(
            (len(pages), self._adapter_page_floats), np.float32
        )
        buf.reshape(-1)[: flat.size] = flat
        ids = jnp.asarray(pages, jnp.int32)
        self._lora_slab = self._lora_slab.at[ids].set(jnp.asarray(buf))

    def _admit_adapter(self, req: Request) -> list[int] | None:
        """Pin ``req``'s adapter for one slot, loading it on a miss.
        None means the pool cannot hold the adapter right now — the
        caller leaves the request at the head of the queue (strict
        admission order holds) and retries next iteration. Stall seconds
        (synchronous load time plus any head-blocked wait) accumulate
        into the miss-stall histogram, flushed by
        :meth:`_publish_adapters`."""
        t0 = time.perf_counter()
        got = self.adapters.acquire(
            req.adapter_id, tier=_TIER_CLASS[req.tier]
        )
        if got is None:
            self._adapter_waits.setdefault(req.rid, t0)
            return None
        pages, loaded = got
        if loaded:
            self._write_adapter_pages(req.adapter_id, pages)
        waited = t0 - self._adapter_waits.pop(req.rid, t0)
        stall = waited + (time.perf_counter() - t0 if loaded else 0.0)
        if loaded or waited > 0.0:
            self._adapter_stalls.append(stall)
        return pages

    def _prefetch_adapter(self, req: Request) -> None:
        """Load-on-arrival: overlap the adapter's slab load with the
        request's queue wait. The adapter is resident-but-unpinned
        afterwards (admission's acquire is a hit) and the prefetch is
        never destructive — it only claims FREE pages, evicting
        nothing."""
        if self.adapters.resident(req.adapter_id):
            return
        if self.allocator.free_pages < self.pages_per_adapter:
            return
        got = self.adapters.acquire(
            req.adapter_id, tier=_TIER_CLASS[req.tier]
        )
        if got is None:
            return
        pages, loaded = got
        t0 = time.perf_counter()
        if loaded:
            self._write_adapter_pages(req.adapter_id, pages)
            # the load happened off the admission path, but it IS a miss
            # load — the histogram counts every slab load the store paid
            self._adapter_stalls.append(time.perf_counter() - t0)
        self.adapters.release(req.adapter_id)

    def warmup(self) -> None:
        """Compile every paged program off the clock, then flush the
        synthetic requests' footprint: radix adoptions, telemetry, and
        the preemption counter all reset to a cold start.

        A speculative engine needs TWO synthetic passes: the parent's
        2-token request always falls below the speculation threshold
        (one remaining token never drafts), compiling prefill/extend and
        the plain decode program; a second request with ``spec_k + 2``
        token budget then forces one draft/verify round. Without both, a
        mid-run first trace of whichever path warmup skipped would break
        the zero-retrace gate."""
        super().warmup()
        if self.draft_params is not None:
            plen = self.chunk + 1
            if max(2 * self.chunk, plen + self.spec_k + 2) > self.max_len:
                plen = min(self.chunk, self.max_len - (self.spec_k + 2))
            if plen >= 1:
                self._warming = True
                try:
                    self.run([Request(
                        rid=-1, prompt=tuple(range(1, plen + 1)),
                        max_new=self.spec_k + 2, arrival=0.0,
                    )])
                finally:
                    self._warming = False
            self.ticks = 0
            self.profiler.reset()
            self._spec_draft_steps = 0
            self._spec_rollback_pages = 0
            self._spec_pub = {"draft_steps": 0, "rollback": 0}
            self._spec_accept_hist = {}
            self._spec_step_hist = {}
            self._spec_tiers = {}
            self._spec_lookahead_high = 0
        if self.radix is not None:
            self.radix.clear()
            self.radix.reset_stats()
        if self.adapters is not None:
            # warmup traffic must not pre-warm the measured hit ratio
            # (the radix clear/reset rule, applied to adapters)
            self.adapters.clear()
            self.adapters.reset_stats()
            self._adapter_pub = {"hits": 0, "misses": 0, "evictions": 0}
            self._adapter_stalls = []
            self._adapter_waits = {}
        self.allocator.reset_stats()
        self.preemptions = 0

    def publish_metrics(self) -> None:
        """Export cache occupancy / prefix-hit / preemption telemetry to
        the ``/metrics`` registry (rendered by ``kubectl-inspect-tpushare``
        next to the gang/slice columns)."""
        labels = {"pod": self.metrics_pod} if self.metrics_pod else {}
        self.allocator.publish(REGISTRY, pod=self.metrics_pod)
        self._flush_step_profile()
        if self.radix is not None:
            REGISTRY.gauge_set(
                ENGINE_PREFIX_HIT_RATIO, self.radix.hit_ratio(),
                "Fraction of looked-up prompt tokens served from the "
                "radix prefix cache", **labels,
            )
            REGISTRY.gauge_set(
                ENGINE_PREFIX_CACHED_PAGES,
                self.radix.cached_pages,
                "KV pages held by the radix prefix cache", **labels,
            )
        REGISTRY.gauge_set(
            ENGINE_PREEMPTIONS, self.preemptions,
            "Requests preempted by page eviction since engine start",
            **labels,
        )
        self._publish_spec(labels)
        self._publish_adapters(labels)

    def _publish_adapters(self, labels: dict) -> None:
        """Batch-flush the adapter-cache families (the
        :meth:`_publish_spec` pattern, never per step): residency gauges,
        counter DELTAS since the last flush, and the accumulated per-load
        miss-stall seconds wrapped in a short ``serve.adapter_load`` span
        so the histogram buckets carry trace-id exemplars."""
        if self.adapters is None or self._warming:
            return
        REGISTRY.gauge_set(
            ENGINE_ADAPTER_ENABLED, 1.0,
            "1 when this engine serves per-request LoRA adapters "
            "(a lora_store is attached)", **labels,
        )
        self.adapters.publish(REGISTRY, pod=self.metrics_pod)
        for fam, cur, key, help_ in (
            (ENGINE_ADAPTER_HITS_TOTAL, self.adapters.hits, "hits",
             "Adapter acquisitions served from the resident slab"),
            (ENGINE_ADAPTER_MISSES_TOTAL, self.adapters.misses, "misses",
             "Adapter acquisitions that had to load from the store"),
            (ENGINE_ADAPTER_EVICTIONS_TOTAL, self.adapters.evictions,
             "evictions",
             "Idle adapters evicted from the slab (LRU, tier-shielded)"),
        ):
            delta = cur - self._adapter_pub[key]
            if delta:
                REGISTRY.counter_inc(
                    fam, help_, value=float(delta), **labels
                )
                self._adapter_pub[key] = cur
        stalls, self._adapter_stalls = self._adapter_stalls, []
        if stalls:
            with TRACER.span(
                "serve.adapter_load", attributes={"loads": len(stalls)},
            ):
                for v in stalls:
                    REGISTRY.observe(
                        ENGINE_ADAPTER_MISS_STALL_SECONDS, float(v),
                        "Seconds a request stalled on (or its queue wait "
                        "overlapped with) its adapter's slab load",
                        buckets=(0.0005, 0.002, 0.01, 0.05, 0.25, 1.0,
                                 5.0),
                        **labels,
                    )

    def _publish_spec(self, labels: dict) -> None:
        """Batch-flush the speculative-decoding families (never per
        step): counter deltas since the last flush plus the accumulated
        acceptance histograms, wrapped in short ``serve.draft`` /
        ``serve.verify`` spans so the buckets carry trace-id exemplars
        (the ``serve.step_flush`` pattern)."""
        if self.draft_params is None or self._warming:
            # warmup's synthetic draft round must never reach /metrics
            # (counters cannot be un-published; same rule as the step
            # profiler's suppressed flush)
            return
        REGISTRY.gauge_set(
            ENGINE_SPEC_ENABLED, 1.0,
            "1 when this engine decodes speculatively (draft model loaded)",
            **labels,
        )
        REGISTRY.gauge_set(
            ENGINE_SPEC_K, float(self.spec_k),
            "Draft proposal length per speculative round", **labels,
        )
        delta = self._spec_draft_steps - self._spec_pub["draft_steps"]
        if delta:
            REGISTRY.counter_inc(
                ENGINE_SPEC_DRAFT_STEPS_TOTAL,
                "Draft-model lookahead dispatches (one per speculative "
                "round)", value=float(delta), **labels,
            )
            self._spec_pub["draft_steps"] = self._spec_draft_steps
        delta = self._spec_rollback_pages - self._spec_pub["rollback"]
        if delta:
            REGISTRY.counter_inc(
                ENGINE_SPEC_ROLLBACK_PAGES_TOTAL,
                "KV pages released by rejected-draft rollback (both "
                "pools' entries freed by refcount)",
                value=float(delta), **labels,
            )
            self._spec_pub["rollback"] = self._spec_rollback_pages
        accept, self._spec_accept_hist = self._spec_accept_hist, {}
        if accept:
            with TRACER.span(
                "serve.draft",
                attributes={
                    "rounds": sum(accept.values()), "k": self.spec_k,
                },
            ):
                for val, n in sorted(accept.items()):
                    for _ in range(n):
                        REGISTRY.observe(
                            ENGINE_SPEC_ACCEPTANCE_LEN, float(val),
                            "Accepted draft tokens per row per "
                            "speculative round (0..k; the emitted "
                            "correction token is not counted)",
                            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0,
                                     16.0),
                            **labels,
                        )
        steps, self._spec_step_hist = self._spec_step_hist, {}
        if steps:
            with TRACER.span(
                "serve.verify",
                attributes={"steps": sum(steps.values())},
            ):
                for val, n in sorted(steps.items()):
                    for _ in range(n):
                        REGISTRY.observe(
                            ENGINE_SPEC_ACCEPTED_TOKENS_PER_STEP,
                            float(val),
                            "Tokens emitted per verify dispatch, summed "
                            "over the round's speculating rows",
                            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0,
                                     64.0, 128.0),
                            **labels,
                        )

    def cache_stats(self) -> dict:
        """The engine-cache telemetry row (``ServeStats.engine_cache``)."""
        out = {
            "total_pages": self.total_pages,
            "free_pages": self.allocator.free_pages,
            "used_pages": self.allocator.used_pages,
            "high_water_pages": self.allocator.high_water,
            "page_size": self.page_size,
            "preemptions": self.preemptions,
        }
        if self.radix is not None:
            out.update(
                prefix_hit_ratio=round(self.radix.hit_ratio(), 4),
                prefix_hit_requests=self.radix.hit_requests,
                prefix_cached_pages=self.radix.cached_pages,
                prefix_evicted_pages=self.radix.evicted_pages,
            )
        if self.governor is not None:
            out["governor"] = self.governor.stats()
        if self.adapters is not None:
            out["adapters"] = {
                "enabled": True,
                "pages_per_adapter": self.adapters.pages_per_adapter,
                **self.adapters.stats(),
            }
        if self.draft_params is not None:
            tiers = {
                t: dict(row) for t, row in sorted(self._spec_tiers.items())
            }
            out["speculative"] = {
                "enabled": True,
                "k": self.spec_k,
                "draft_steps": self._spec_draft_steps,
                "proposed": sum(r["proposed"] for r in tiers.values()),
                "accepted": sum(r["accepted"] for r in tiers.values()),
                "rollback_pages": self._spec_rollback_pages,
                # Draft-pool occupancy IS KV occupancy — one table, one
                # refcount, two parallel pools — reported beside it so
                # dashboards can see the draft rode along at page parity.
                "draft_pool_used_pages": self.allocator.used_pages,
                "lookahead_high_water_pages": self._spec_lookahead_high,
                "tiers": tiers,
            }
        return out

    # --- drain/restore: the defrag move protocol's engine hand-off --------

    def request_drain(self) -> None:
        """Ask the in-progress :meth:`run` to quiesce at its next
        iteration boundary: admission stops, every unfinished request is
        captured into :meth:`drain_snapshot`, and its pages are freed.
        Thread-safe — the defragmenter's ``drain_fn`` calls this from the
        move protocol's ``drain`` phase while the serving thread loops; a
        drain requested while the engine is idle captures the next run's
        whole queue immediately. A cross-thread caller must then
        :meth:`wait_drained` — reading :meth:`drain_snapshot` before the
        serving thread reaches the boundary returns stale/None and the
        eventual snapshot would never be collected. (Arm semantics —
        why only this re-arm may discard an uncollected capture — are
        documented on :meth:`.drainproto.DrainHandshake.request`.)"""
        self._drain.request()

    def wait_drained(self, timeout: float | None = None) -> dict | None:
        """Block until the serving thread quiesced after
        :meth:`request_drain` — either it captured a drain snapshot or
        its :meth:`run` completed with nothing left in flight — then
        return :meth:`drain_snapshot` (None in the ran-to-completion
        case: every request retired, nothing to move). Raises
        ``TimeoutError`` when ``timeout`` (seconds) expires with no run
        reaching a boundary — the mover treats a ``drain_fn`` exception
        as a failed move, and the not-quiesced case MUST be
        distinguishable from the clean nothing-in-flight None: a mover
        that read None from a wedged engine would flip the pod's
        accounting while the source is still actively serving.

        A timed-out wait DISARMS the drain before raising: the move is
        dead, and an engine left armed would quiesce its next unrelated
        run immediately — every request captured into a snapshot nobody
        collects (lost). If the serving thread reached the boundary in
        the instant between the wait expiring and the disarm, that
        capture is taken instead of raised away."""
        return self._drain.wait(timeout)

    def drain_snapshot(self) -> dict | None:
        """The JSON-safe in-flight snapshot captured by the last drained
        :meth:`run` (None when the last quiesce ended with everything
        retired; an uncollected capture survives back-to-back runs until
        the next :meth:`request_drain` re-arms the cycle): engine
        geometry plus one row per unfinished request — prompt, tokens
        generated so far, tier/SLO targets, queue state. Everything the
        destination engine needs to continue the request with greedy
        tokens bit-identical to an unmoved run. KV bytes are deliberately
        NOT carried: restore re-prefills prompt + generated tokens (the
        preemption re-admission math), and radix-shared prefixes
        re-resolve against the destination engine's own cache."""
        return self._drain.snapshot()

    def _drain_row(
        self, req: Request, res: RequestResult | None, state: str
    ) -> dict:
        return {
            "rid": req.rid,
            "state": state,
            "prompt": list(req.prompt),
            "max_new": req.max_new,
            "arrival": float(req.arrival),
            "tier": req.tier,
            "slo_ttft_ticks": req.slo_ttft_ticks,
            "slo_tpot_ticks": req.slo_tpot_ticks,
            # the destination engine re-pins the tenant's adapter at
            # re-admission (its own cache/slab — ids, never weights,
            # cross the move)
            "adapter_id": req.adapter_id,
            "tokens": list(res.tokens) if res is not None else [],
        }

    def restore_snapshot(self, snapshot: dict | None) -> ServeStats:
        """Re-admit a drained snapshot on THIS engine (the move's
        destination slice) and serve it to completion. Each restored
        request re-prefills its prompt plus its pre-drain tokens — greedy
        decoding is deterministic, so the continuation (and therefore the
        combined token list in the returned results) is bit-identical to
        a run that was never drained. Raises on an eos/kv-dtype mismatch
        with the snapshot's source engine: those change WHAT tokens come
        out, and a silent divergence is exactly what the move protocol's
        bit-identity contract forbids (pool geometry — slots, pages,
        max_len — may differ; that only changes WHERE bytes live —
        but every snapshot request must still pass the destination's
        :meth:`validate`: a destination whose ``max_len`` cannot hold a
        request's prompt + budget raises, and the mover/reconciler keeps
        the move pending rather than committing away the journal's only
        copy — plan moves between same-geometry engines).

        Idempotent per delivery: the move protocol's restore delivery is
        AT-LEAST-ONCE — a daemon killed between the mover's restore and
        its WAL commit rolls forward at restart and re-delivers the same
        journaled snapshot to this (still running) engine. The mover
        stamps each journaled snapshot with a ``snapshot_id`` unique to
        the move attempt; an id this instance already restored is a
        logged no-op, so the duplicate delivery can never serve the
        drained requests twice. A snapshot WITHOUT an id (a source-side
        supervisor re-serving its own drain after a rollback) is never
        deduplicated — identity, not content, is the key: two
        independent moves of a deterministic workload legitimately carry
        byte-identical snapshots."""
        if not snapshot or not snapshot.get("requests"):
            return ServeStats(
                results=[], ticks=0, wall_s=0.0,
                trace_counts=dict(self.trace_counts),
            )
        snap_id = snapshot.get("snapshot_id")
        if snap_id is not None and snap_id in self._restored_ids:
            log.warning(
                "restore_snapshot: snapshot %s already restored on this "
                "engine; duplicate delivery ignored", snap_id,
            )
            return ServeStats(
                results=[], ticks=0, wall_s=0.0,
                trace_counts=dict(self.trace_counts),
            )
        eng = snapshot.get("engine") or {}
        if eng.get("eos_id", self.eos_id) != self.eos_id or (
            eng.get("kv_dtype", self.kv_dtype) != self.kv_dtype
        ):
            raise ValueError(
                f"snapshot from engine {eng} cannot restore here "
                f"(eos_id={self.eos_id}, kv_dtype={self.kv_dtype}) — "
                "greedy tokens would silently diverge"
            )
        reqs: list[Request] = []
        seeds: dict[int, tuple[int, ...]] = {}
        for row in snapshot["requests"]:
            req = Request(
                rid=int(row["rid"]),
                prompt=tuple(int(t) for t in row["prompt"]),
                max_new=int(row["max_new"]),
                arrival=0.0,  # every drained request has already arrived
                tier=str(row.get("tier", TIER_CRITICAL)),
                slo_ttft_ticks=row.get("slo_ttft_ticks"),
                slo_tpot_ticks=row.get("slo_tpot_ticks"),
                adapter_id=str(row.get("adapter_id") or ""),
            )
            reqs.append(req)
            seeds[req.rid] = tuple(int(t) for t in row.get("tokens") or ())
        self._restore_tokens = seeds
        try:
            stats = self.run(reqs)
        finally:
            self._restore_tokens = {}
        # recorded only after the run quiesced (served to completion or
        # drained into a fresh snapshot): a restore that died mid-run
        # stays re-deliverable
        if snap_id is not None:
            self._restored_ids.append(snap_id)
        return stats

    # --- disaggregated prefill/decode handoff (serving/handoff.py) --------

    def set_handoff_sink(self, sink) -> None:
        """Arm (or clear, with None) the prefill-tier export sink:
        ``sink(export_dict)`` is called synchronously from :meth:`run`
        for every request that completes its prompt with decode work
        remaining — AFTER the row retired here (its pages are already
        fetched to host inside the dict). The sink side is
        ``serving/handoff.py``: it serializes the pages and drives the
        journaled handoff to the decode tier."""
        self._handoff_sink = sink

    def export_kv_pages(self, page_ids: Sequence[int]) -> list[dict]:
        """Fetch the KV contents of ``page_ids`` to host, one dict of
        numpy arrays per page (every cache buffer except the per-slot
        ``len`` vector, sliced on the page axis). Pages are read, never
        mutated — radix-shared pages export safely."""
        out = []
        for p in page_ids:
            out.append({
                key: np.asarray(val[:, int(p)])
                for key, val in self.cache.items() if key != "len"
            })
        return out

    def import_kv_pages(self, page_ids: Sequence[int], blobs: Sequence[dict]) -> None:
        """Write transferred page contents (as produced by
        :meth:`export_kv_pages` on the source engine) into this engine's
        pages ``page_ids``. Raises ``ValueError`` on any geometry
        mismatch BEFORE touching the cache — the handoff sink degrades
        such a delivery to local re-prefill rather than adopting pages
        that would decode garbage. One eager batched scatter per cache
        buffer: off the jit'd hot path, so zero retraces."""
        if len(page_ids) != len(blobs):
            raise ValueError(
                f"import_kv_pages: {len(page_ids)} pages but "
                f"{len(blobs)} payloads"
            )
        if not page_ids:
            return
        ids = jnp.asarray([int(p) for p in page_ids], jnp.int32)
        staged = {}
        for key, val in self.cache.items():
            if key == "len":
                continue
            try:
                stacked = np.stack(
                    [np.asarray(b[key]) for b in blobs], axis=1
                )
            except KeyError as e:
                raise ValueError(
                    f"import_kv_pages: payload missing cache buffer {e}"
                ) from None
            expected = (val.shape[0], len(blobs)) + tuple(val.shape[2:])
            if tuple(stacked.shape) != expected:
                raise ValueError(
                    f"import_kv_pages: buffer {key!r} shape "
                    f"{stacked.shape} does not fit this engine's "
                    f"{expected} (source engine geometry differs)"
                )
            staged[key] = stacked
        for key, stacked in staged.items():
            self.cache[key] = self.cache[key].at[:, ids].set(
                jnp.asarray(stacked, self.cache[key].dtype)
            )

    def seed_handoff_import(
        self, rid: int, *, pages: Sequence[int], pos: int, last: int,
        prompt: Sequence[int],
    ) -> None:
        """Stage one imported request for the next :meth:`run`: when a
        request with this rid reaches the head of admission it adopts
        ``pages`` (whose KV must already be written via
        :meth:`import_kv_pages`, covering logical positions
        ``[0, pos)``) directly into decode state with ``last`` as its
        next input token. Page ownership transfers to the row — retire
        or preemption releases them through this engine's allocator, so
        the caller must have allocated them there."""
        self._import_seeds[int(rid)] = {
            "pages": [int(p) for p in pages],
            "pos": int(pos),
            "last": int(last),
            "prompt": tuple(int(t) for t in prompt),
        }

    def seed_restore_tokens(self, seeds: dict) -> None:
        """Seed already-generated tokens for rids the next :meth:`run`
        will serve (the restore-path re-admission math): each request's
        result starts with these tokens and admission re-prefills
        ``prompt + tokens``, so the retired token list is the combined
        stream — bit-identical by greedy determinism. The handoff path
        uses this for every handed-off request (the prefill tier's first
        token), which is also exactly what makes the re-prefill
        fallback lossless."""
        for rid, toks in seeds.items():
            self._restore_tokens[int(rid)] = tuple(int(t) for t in toks)

    def clear_handoff_seeds(self) -> None:
        """Drop restore-token seeds and any unconsumed import seeds,
        releasing the latter's pages (a seeded rid that never arrived
        must not leak its reservation)."""
        self._restore_tokens = {}
        leftovers = self._import_seeds
        self._import_seeds = {}
        for seed in leftovers.values():
            if seed["pages"]:
                self.allocator.release(seed["pages"])

    def _export_handoff(self, s: "_PagedSlot", t: int) -> dict:
        """Build the prefill-tier export for one just-completed prompt:
        the JSON-safe request row (the re-prefill guarantee — everything
        the decode tier needs WITHOUT the KV), engine geometry, and the
        row's KV pages fetched to host. Called BEFORE retire frees the
        pages."""
        row = self._drain_row(s.req, s.result, "handoff")
        row["prompt"] = list(s.prompt)  # effective prompt the pages hold
        n = pages_for(s.pos, self.page_size)
        return {
            "request": row,
            "pos": int(s.pos),
            "first_token": int(t),
            "first_token_tick": int(self.ticks),
            "meta": {
                "page_size": self.page_size,
                "kv_dtype": self.kv_dtype,
                "eos_id": self.eos_id,
                "pos": int(s.pos),
                "n_pages": n,
            },
            "pages": self.export_kv_pages(s.pages[:n]),
        }

    # --- page bookkeeping -------------------------------------------------

    def _fresh_slot(self) -> _PagedSlot:
        s = _PagedSlot(
            table=np.full((self.row_pages,), SCRATCH, np.int32)
        )
        if self.adapters is not None:
            # all-SCRATCH adapter table = the null adapter: slab row 0
            # is permanently zero, so base-model rows gather an
            # exactly-zero delta through the same one dispatch
            s.atable = np.full(
                (self.pages_per_adapter,), SCRATCH, np.int32
            )
        return s

    def _grow(self, s: _PagedSlot, got: list[int]) -> None:
        """Append freshly-granted pages to a row and map them in its
        table (allocated entries are always a prefix of the row)."""
        base = len(s.pages)
        s.pages.extend(got)
        s.table[base : base + len(got)] = got

    def run(
        self,
        requests: Sequence[Request],
        *,
        drain_at_tick: int | None = None,
    ) -> ServeStats:
        """Serve to completion with paged admission. Per iteration:
        (1) enqueue arrivals, (2) admit pending requests in (tier,
        arrival) order — radix-matching each prompt and allocating first
        -chunk pages, evicting radix LRU pages and then preempting
        best-effort victims when a critical request is short, (3) one
        prompt chunk for the oldest mid-prefill row, (4) one pool-wide
        decode step over rows whose next position is page-backed. A row
        that cannot get its next page stalls in place (its neighbors
        keep decoding) until pages free up or preemption policy frees
        them.

        ``drain_at_tick`` (or a concurrent :meth:`request_drain`) ends
        the run at the next iteration boundary once the tick clock
        reaches it: unfinished requests move into
        :meth:`drain_snapshot`, their pages are freed, and only already-
        retired results are returned — the engine half of a
        defragmentation move (``allocator/defrag.py``)."""
        for r in requests:
            self.validate(r)
        self.ticks = 0
        # deliberately NOT resetting the drain handshake here: an
        # uncollected capture from a prior run must survive a
        # back-to-back run() start until its waiter reads it — only
        # request_drain() (re-arming a new cycle) may discard it
        incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
        slots = [self._fresh_slot() for _ in range(self.n_slots)]
        pending: list[Request] = []
        results: list[RequestResult] = []
        live: dict[int, RequestResult] = {}
        i = 0
        t0 = time.perf_counter()
        base_ns = time.time_ns()
        ps = self.page_size

        def now() -> float:
            return time.perf_counter() - t0

        def tier_key(req: Request) -> tuple:
            return (0 if req.tier == TIER_CRITICAL else 1, req.arrival,
                    req.rid)

        # LoRA trailing args for the jitted programs: the device slab
        # plus the dispatch's adapter page tables ([1, AP] for the
        # single-row prefill/extend, [n_slots, AP] for pool-wide steps;
        # idle rows gather the null adapter). Always passed when the
        # engine carries a store — mixed-tenant batches are one dispatch
        # and the adapter mix can never retrace.
        lora_on = self.adapters is not None

        def slot_lw(s: _PagedSlot) -> tuple:
            return (self._lora_slab, jnp.asarray(s.atable[None]))

        def pool_lw(rows) -> tuple:
            at = np.full(
                (self.n_slots, self.pages_per_adapter), SCRATCH, np.int32
            )
            for idx in rows:
                at[idx] = slots[idx].atable
            return (self._lora_slab, jnp.asarray(at))

        def release_row(s: _PagedSlot) -> None:
            if s.pages:
                self.allocator.release(s.pages)
            s.pages = []
            s.table[:] = SCRATCH
            if (
                self.adapters is not None and s.req is not None
                and s.req.adapter_id
            ):
                # unpin the tenant's adapter: it stays resident (the
                # next request for it is a hit) but becomes evictable
                self.adapters.release(s.req.adapter_id)
                s.atable[:] = SCRATCH

        def preempt_one(critical_only: bool = True,
                        protect: int | None = None) -> bool:
            """Evict one victim's pages and re-queue its request. Victims
            are best-effort rows, youngest admission first; with
            ``critical_only=False`` (the zero-progress fallback) any tier
            may be chosen except the protected (oldest) row, so the
            oldest request makes monotonic progress and the loop
            terminates."""
            cands = [
                (idx, s) for idx, s in enumerate(slots)
                if s.state != "free" and idx != protect
                and (s.req.tier == TIER_BEST_EFFORT or not critical_only)
            ]
            if not cands:
                return False
            # best-effort before critical, then youngest admission
            idx, s = max(
                cands,
                key=lambda p: (p[1].req.tier == TIER_BEST_EFFORT,
                               p[1].req.arrival, p[1].req.rid),
            )
            res = s.result
            res.preemptions.append(
                {"evict_tick": self.ticks, "evict_s": now()}
            )
            self.preemptions += 1
            labels = (
                {"pod": self.metrics_pod} if self.metrics_pod else {}
            )
            REGISTRY.counter_inc(
                ENGINE_PREEMPTIONS_TOTAL,
                "Paged-engine preemptions (victim pages evicted for a "
                "higher-priority request)", **labels,
            )
            release_row(s)
            pending.append(s.req)
            slots[idx] = self._fresh_slot()
            return True

        def try_pages(n: int, tier: str) -> list[int] | None:
            """All-or-nothing grant of ``n`` pages: free list first, then
            radix LRU eviction (cache shrink — allowed for any tier),
            then best-effort preemption for critical requesters.

            The destructive steps are gated on ``freeable``: unless
            releasing the whole escalation set (cached pages, plus
            best-effort victims' rows for a critical requester) would
            actually cover ``n``, nothing is evicted — a doomed grant
            must not dump the prefix cache or destroy victims' decode
            progress only to leave the requester blocked anyway."""
            got = self.allocator.alloc(n)
            if got is not None:
                return got
            groups: list[list[int]] = []
            if self.adapters is not None:
                groups.extend(
                    self.adapters.evictable(tier=_TIER_CLASS[tier])
                )
            if self.radix is not None:
                groups.append(self.radix.pages())
            if tier == TIER_CRITICAL:
                groups.extend(
                    s.pages for s in slots
                    if s.state != "free" and s.req.tier == TIER_BEST_EFFORT
                )
            if self.allocator.free_pages + self.allocator.freeable(
                groups
            ) < n:
                return None
            # eviction ladder for KV: idle adapters reclaim FIRST — an
            # unpinned adapter can be re-read from the store for one
            # load, a cached prefix costs a re-prefill, a preempted row
            # loses live decode progress
            if self.adapters is not None:
                while self.allocator.free_pages < n:
                    if not self.adapters.evict(
                        n - self.allocator.free_pages,
                        tier=_TIER_CLASS[tier],
                    ):
                        break
                got = self.allocator.alloc(n)
                if got is not None:
                    return got
            if self.radix is not None:
                while self.allocator.free_pages < n:
                    if not self.radix.evict(n - self.allocator.free_pages):
                        break
                got = self.allocator.alloc(n)
                if got is not None:
                    return got
            if tier == TIER_CRITICAL:
                while self.allocator.free_pages < n:
                    if not preempt_one():
                        break
                got = self.allocator.alloc(n)
            return got

        def retire(idx: int) -> None:
            s = slots[idx]
            res = s.result
            res.finish_tick = self.ticks
            res.finish_s = now()
            results.append(res)
            self._record_request_trace(res, base_ns)
            self._note_slo(res)
            # Adopt the ORIGINAL prompt's full pages into the radix tree
            # (they hold exactly those tokens' KV; pages past the prompt
            # mix in generated content and are simply freed). The tree
            # takes its own reference, so releasing the engine's below
            # recycles only the unshared tail.
            # draft_stale rows never adopt: their pages' draft-pool
            # entries were never prefilled (handoff imports carry target
            # KV only), and a future prefix match would speculate over
            # garbage draft state — silently wrong proposals cost
            # acceptance, and the cache poisoning would outlive the row
            if self.radix is not None and s.req.rid >= 0 and not s.draft_stale:
                full = len(s.req.prompt) // ps
                if full:
                    self.radix.insert(
                        tuple(s.req.prompt[: full * ps]), s.pages[:full]
                    )
            release_row(s)
            slots[idx] = self._fresh_slot()

        while i < len(incoming) or pending or any(
            s.state != "free" for s in slots
        ):
            if self._drain.armed() or (
                drain_at_tick is not None and self.ticks >= drain_at_tick
            ):
                # quiesce: capture every unfinished request (in-flight
                # rows, the pending queue — a preempted-then-drained
                # request sits here with its regenerated tokens — and
                # arrivals this run never reached), free the pool, and
                # stop. Retired results below are the only ones returned.
                rows = []
                for s in sorted(
                    (s for s in slots if s.state != "free"),
                    key=lambda s: (s.req.arrival, s.req.rid),
                ):
                    rows.append(self._drain_row(s.req, live[s.req.rid], "slot"))
                    release_row(s)
                for req in sorted(pending, key=tier_key):
                    rows.append(self._drain_row(req, live[req.rid], "pending"))
                for req in incoming[i:]:
                    row = self._drain_row(req, None, "queued")
                    # a restored-but-never-enqueued request keeps its
                    # pre-drain tokens: until the arrival loop seeds
                    # live[], the only copy is _restore_tokens — without
                    # this a second move would regenerate from scratch
                    # and break the bit-identity contract
                    seed = self._restore_tokens.get(req.rid)
                    if seed:
                        row["tokens"] = list(seed)
                    rows.append(row)
                captured = {
                    "version": 1,
                    "drain_tick": self.ticks,
                    "engine": {
                        "slots": self.n_slots, "max_len": self.max_len,
                        "page_size": self.page_size,
                        "prefill_chunk": self.chunk,
                        "total_pages": self.total_pages,
                        "eos_id": self.eos_id, "kv_dtype": self.kv_dtype,
                    },
                    "requests": rows,
                }
                self._drain.publish(captured)  # wake cross-thread wait_drained
                break
            while i < len(incoming) and incoming[i].arrival <= self.ticks:
                req = incoming[i]
                live[req.rid] = RequestResult(
                    rid=req.rid, prompt_len=len(req.prompt),
                    # restore path: pre-drain tokens seed the result, so
                    # admission re-prefills prompt + tokens (the
                    # preemption re-admission math) and the retired
                    # token list is the COMBINED stream
                    tokens=list(self._restore_tokens.get(req.rid, ())),
                    arrival_tick=req.arrival, arrival_s=now(),
                    tier=req.tier, slo_ttft_ticks=req.slo_ttft_ticks,
                    slo_tpot_ticks=req.slo_tpot_ticks,
                )
                pending.append(req)
                if self.adapters is not None and req.adapter_id:
                    # overlap the slab load with the queue wait
                    self._prefetch_adapter(req)
                i += 1
            busy = any(s.state != "free" for s in slots)
            if not busy and not pending:
                self.ticks = max(
                    self.ticks, int(math.ceil(incoming[i].arrival))
                )
                continue
            dispatched = False

            # --- admission: strict (tier, arrival) order; a blocked head
            # blocks the line so best-effort can never overtake a
            # page-starved critical request
            free_rows = [
                idx for idx, s in enumerate(slots) if s.state == "free"
            ]
            while pending and free_rows:
                # re-sort each pass: a preemption inside try_pages can
                # re-queue its victim mid-loop
                pending.sort(key=tier_key)
                req = pending[0]
                res = live[req.rid]
                apages = None
                if self.adapters is not None and req.adapter_id:
                    # pin the tenant's adapter BEFORE any KV is granted:
                    # a pinned adapter is shielded from the KV rungs'
                    # eviction below. None = no slab capacity — the head
                    # blocks the line (strict admission order holds, the
                    # page-starved-head rule) and retries next iteration.
                    apages = self._admit_adapter(req)
                    if apages is None:
                        break
                seed = (
                    self._import_seeds.pop(req.rid, None)
                    if self._import_seeds else None
                )
                if seed is not None:
                    # handoff import (decode tier): this request's
                    # prompt KV already sits in this engine's pages —
                    # adopt it straight into decode state, no prefill.
                    # Page ownership moves seed -> row: retire or a
                    # later preemption releases through the allocator
                    # (a preempted import re-queues and re-prefills
                    # prompt + tokens — still bit-identical).
                    pending.pop(0)
                    idx = free_rows.pop(0)
                    s = slots[idx]
                    s.state = "decode"
                    s.req = req
                    s.prompt = tuple(seed["prompt"])
                    s.done = s.pos = int(seed["pos"])
                    s.result = res
                    if apages is not None:
                        s.atable[:] = apages
                    self._grow(s, list(seed["pages"]))
                    s.shared = 0
                    s.last = int(seed["last"])
                    if not res.tokens:
                        res.tokens.append(s.last)
                    res.admit_tick = self.ticks
                    res.admit_s = now()
                    if res.first_token_tick is None:
                        # the first token arrived WITH the handoff
                        res.first_token_tick = self.ticks
                        res.first_token_s = now()
                    # seed the device-side row length so the decode
                    # kernel writes/attends at the right positions
                    # (eager, off the jit'd path: zero retraces)
                    self.cache["len"] = self.cache["len"].at[idx].set(
                        int(seed["pos"])
                    )
                    if self.draft_params is not None:
                        # imported pages carry TARGET KV only: park the
                        # row on the plain decode path for its lifetime
                        # (a preempted import re-prefills BOTH pools on
                        # re-admission and speculates again)
                        s.draft_stale = True
                        self.draft_cache["len"] = (
                            self.draft_cache["len"].at[idx].set(
                                int(seed["pos"])
                            )
                        )
                    continue
                eff = req.prompt + tuple(res.tokens)
                matched, mpages = 0, []
                if self.radix is not None:
                    # count=False: a page-starved head re-matches every
                    # iteration it stays blocked; the lookup is recorded
                    # once below, when the admission lands
                    matched, mpages = self.radix.match(eff, count=False)
                    # floor to a chunk boundary: the chunk walk then
                    # lands exactly where a fresh prefill's would, so
                    # the padded write extent never grows past the table
                    aligned = (matched // self.chunk) * self.chunk
                    keep = aligned // ps
                    if keep < len(mpages):
                        self.allocator.release(mpages[keep:])
                        mpages = mpages[:keep]
                        matched = aligned
                first_real = min(self.chunk, len(eff) - matched)
                need = pages_for(matched + first_real, ps) - len(mpages)
                fresh = try_pages(max(need, 0), req.tier)
                if fresh is None:
                    if mpages:
                        self.allocator.release(mpages)
                    if apages is not None:
                        # the KV grant failed after the adapter pinned:
                        # unpin so the idle adapter stays evictable for
                        # whoever CAN make progress (re-pinning on the
                        # retry is a hit while it stays resident)
                        self.adapters.release(req.adapter_id)
                    break
                pending.pop(0)
                if self.radix is not None:
                    self.radix.record_lookup(len(eff), matched)
                idx = free_rows.pop(0)
                s = slots[idx]
                s.state = "prefill"
                s.req = req
                s.prompt = eff
                s.done = matched
                s.pos = matched
                s.result = res
                if apages is not None:
                    s.atable[:] = apages
                self._grow(s, mpages)
                s.shared = len(mpages)
                self._grow(s, fresh)
                if res.preemptions and "readmit_tick" not in res.preemptions[-1]:
                    res.preemptions[-1]["readmit_tick"] = self.ticks
                    res.preemptions[-1]["readmit_s"] = now()
                else:
                    res.admit_tick = self.ticks
                    res.admit_s = now()
                if matched and req.rid >= 0:
                    res.prefix_tokens += matched
                    # live span (one per admission, off the per-token
                    # path) so the histogram bucket carries a trace-id
                    # exemplar linking /metrics to /traces
                    with TRACER.span(
                        "serve.prefix_hit",
                        attributes={"rid": req.rid, "tokens": matched},
                    ):
                        REGISTRY.observe(
                            ENGINE_PREFIX_HIT_TOKENS,
                            float(matched),
                            "Prompt tokens served from the radix prefix "
                            "cache per admission",
                            buckets=(1.0, 4.0, 16.0, 64.0, 256.0, 1024.0,
                                     4096.0),
                            **(
                                {"pod": self.metrics_pod}
                                if self.metrics_pod else {}
                            ),
                        )

            # --- one prompt chunk for the oldest mid-prefill row
            pre = [idx for idx, s in enumerate(slots) if s.state == "prefill"]
            if pre:
                idx = min(pre, key=lambda j: slots[j].result.arrival_tick)
                s = slots[idx]
                real = s.prompt[s.done : s.done + self.chunk]
                n_real = len(real)
                need = pages_for(s.done + n_real, ps) - len(s.pages)
                got = try_pages(need, s.req.tier) if need > 0 else []
                # got is None: the row stalls in place, retried next
                # iteration (the decode pool below still dispatches)
                if got is not None:
                    self._grow(s, got)
                    if self.governor is not None:
                        # prefill dispatches are paced like decode steps
                        # (see SlotEngine.run): a best-effort engine must
                        # not leak contention through its prompt chunks
                        self.governor.before_step()
                    buf = np.zeros((self.chunk,), np.int32)
                    buf[:n_real] = real
                    table = jnp.asarray(s.table)
                    lw = slot_lw(s) if lora_on else ()
                    # spec mode runs the draft model over the same chunk
                    # in the SAME dispatch (combined programs), so the
                    # draft pool tracks the target pool in lockstep —
                    # still one model dispatch, one tick
                    if s.done == 0:
                        if self.draft_params is not None:
                            tok, self.cache, self.draft_cache = (
                                self._prefill(
                                    self.params, self.draft_params,
                                    jnp.asarray(buf), self.cache,
                                    self.draft_cache, np.int32(idx),
                                    table, np.int32(n_real), *lw,
                                )
                            )
                        else:
                            tok, self.cache = self._prefill(
                                self.params, jnp.asarray(buf), self.cache,
                                np.int32(idx), table, np.int32(n_real),
                                *lw,
                            )
                    else:
                        if self.draft_params is not None:
                            tok, self.cache, self.draft_cache = (
                                self._extend(
                                    self.params, self.draft_params,
                                    jnp.asarray(buf), self.cache,
                                    self.draft_cache, np.int32(idx),
                                    table, np.int32(s.done),
                                    np.int32(n_real), *lw,
                                )
                            )
                        else:
                            tok, self.cache = self._extend(
                                self.params, jnp.asarray(buf), self.cache,
                                np.int32(idx), table, np.int32(s.done),
                                np.int32(n_real), *lw,
                            )
                    self.ticks += 1
                    dispatched = True
                    s.done += n_real
                    s.pos = s.done
                    if s.done == len(s.prompt):
                        t = int(tok)
                        if not s.result.tokens:
                            s.result.first_token_tick = self.ticks
                            s.result.first_token_s = now()
                        s.result.tokens.append(t)
                        if (
                            self.eos_id is not None and t == self.eos_id
                        ) or len(s.result.tokens) >= s.req.max_new:
                            retire(idx)
                        elif self._handoff_sink is not None and s.req.rid >= 0:
                            # prefill tier: this request's decode belongs
                            # to the peer engine. Fetch the row's KV to
                            # host BEFORE retire frees the pages, retire
                            # (the prompt's pages still adopt into the
                            # local radix for future prefix hits), then
                            # hand the export to the sink.
                            export = self._export_handoff(s, t)
                            retire(idx)
                            self._handoff_sink(export)
                        else:
                            s.state = "decode"
                            s.last = t

            # --- speculative rounds: one draft dispatch proposes k
            # lookahead tokens for every eligible decoding row, one
            # verify dispatch scores the whole block — up to k+1 tokens
            # per row for 2 dispatches (2 ticks). Eligibility is
            # per-row data, never a shape: a row that is ineligible (or
            # page-starved for lookahead) simply plain-decodes below.
            spec_set: set[int] = set()
            if (
                self.draft_params is not None
                and not self._spec_suspended
                # governor engaged: shed DRAFT dispatches first — the
                # lookahead is optional work; the target step below is
                # not. Tokens stay bit-identical either way. Warmup
                # bypasses the shed: draft/verify must compile even on
                # an engine born throttled, or their first trace lands
                # mid-run the moment the governor disengages.
                and (
                    self._warming
                    or self.governor is None
                    or not self.governor.engaged
                )
            ):
                k = self.spec_k
                row_cap = min(self.row_pages * ps, self.max_len)
                lookahead = 0
                for idx, s in enumerate(slots):
                    if s.state != "decode" or s.draft_stale:
                        continue
                    # a round can emit at most k+1 tokens but costs 2
                    # dispatches: with <2 tokens of budget left the
                    # plain path is strictly cheaper
                    if s.req.max_new - len(s.result.tokens) < 2:
                        continue
                    # verify writes positions pos..pos+k: the whole
                    # block must fit the row (RoPE bound included)
                    if s.pos + k + 1 > row_cap:
                        continue
                    need = pages_for(s.pos + k + 1, ps) - len(s.pages)
                    if need > 0:
                        # PLAIN alloc, no escalation: drafts sit below
                        # adapters and KV in the eviction ladder — a
                        # lookahead never evicts radix pages or preempts
                        # a row. Starved rows fall back to plain decode.
                        got = self.allocator.alloc(need)
                        if got is None:
                            continue
                        self._grow(s, got)
                        lookahead += need
                    spec_set.add(idx)
                self._spec_lookahead_high = max(
                    self._spec_lookahead_high, lookahead
                )
            if spec_set:
                spec_rows = sorted(spec_set)
                toks = np.zeros((self.n_slots,), np.int32)
                active = np.zeros((self.n_slots,), bool)
                tables = np.full(
                    (self.n_slots, self.row_pages), SCRATCH, np.int32
                )
                for idx in spec_rows:
                    tables[idx] = slots[idx].table
                    toks[idx] = slots[idx].last
                    active[idx] = True
                if self.governor is not None:
                    self.governor.before_step()
                _step_t0 = time.perf_counter()
                drafts, self.draft_cache = self._draft(
                    self.draft_params, jnp.asarray(toks), self.draft_cache,
                    jnp.asarray(tables), jnp.asarray(active),
                )
                self.ticks += 1
                self._spec_draft_steps += 1
                if self.governor is not None:
                    self.governor.before_step()
                block = jnp.concatenate(
                    [jnp.asarray(toks)[:, None], drafts], axis=1
                )
                greedy, acc, self.cache, self.draft_cache = self._verify(
                    self.params, block, self.cache, self.draft_cache,
                    jnp.asarray(tables), jnp.asarray(active),
                    *(pool_lw(spec_rows) if lora_on else ()),
                )
                self.ticks += 1
                dispatched = True
                drafts_np = np.asarray(drafts)
                greedy_np = np.asarray(greedy)
                acc_np = np.asarray(acc)
                emitted_total = 0
                for idx in spec_rows:
                    s = slots[idx]
                    a_i = int(acc_np[idx])
                    self._spec_accept_hist[a_i] = (
                        self._spec_accept_hist.get(a_i, 0) + 1
                    )
                    trow = self._spec_tiers.setdefault(
                        s.req.tier, {"proposed": 0, "accepted": 0}
                    )
                    trow["proposed"] += k
                    trow["accepted"] += a_i
                    retired = False
                    # emit accepted drafts then the correction token —
                    # exactly the sequential greedy stream (the verify
                    # argmax at position pos+j IS what a plain decode
                    # step at pos+j would have sampled)
                    for j in range(a_i + 1):
                        t = (
                            int(drafts_np[idx, j]) if j < a_i
                            else int(greedy_np[idx, a_i])
                        )
                        s.pos += 1
                        s.result.tokens.append(t)
                        s.last = t
                        emitted_total += 1
                        if (
                            self.eos_id is not None and t == self.eos_id
                        ) or len(s.result.tokens) >= s.req.max_new:
                            retired = True
                            break
                    if retired:
                        retire(idx)
                    else:
                        # rollback: rejected tokens' KV pages release by
                        # refcount. Tail pages past pages_for(pos) are
                        # always this row's fresh lookahead (shared
                        # pages are a prefix <= done <= pos), so the
                        # release never touches radix-shared state;
                        # stale KV inside kept pages beyond pos is
                        # invisible (the decode visibility mask stops at
                        # each row's len).
                        keep = pages_for(s.pos, ps)
                        tail = s.pages[keep:]
                        if tail:
                            self.allocator.release(tail)
                            del s.pages[keep:]
                            s.table[keep:] = SCRATCH
                            self._spec_rollback_pages += len(tail)
                self._spec_step_hist[emitted_total] = (
                    self._spec_step_hist.get(emitted_total, 0) + 1
                )
                self.profiler.record(
                    time.perf_counter() - _step_t0,
                    tokens=emitted_total / len(spec_rows),
                )

            # --- pool-wide decode over page-backed rows (spec-round
            # rows already advanced this iteration and sit the step out)
            dec = [
                idx for idx, s in enumerate(slots)
                if s.state == "decode" and idx not in spec_set
            ]
            for idx in dec:
                s = slots[idx]
                # a try_pages below may preempt a best-effort row LATER
                # in this same pass: its slot is fresh (req=None) by the
                # time we reach it, and must not be granted a page
                if s.state != "decode":
                    continue
                if pages_for(s.pos + 1, ps) > len(s.pages):
                    got = try_pages(1, s.req.tier)
                    if got is not None:
                        self._grow(s, got)
            # a preemption above may have evicted a decode row
            active_rows = [
                idx for idx in dec
                if slots[idx].state == "decode"
                and pages_for(slots[idx].pos + 1, ps) <= len(slots[idx].pages)
            ]
            if active_rows:
                toks = np.zeros((self.n_slots,), np.int32)
                active = np.zeros((self.n_slots,), bool)
                # Rows not decoding get an all-SCRATCH table: their
                # device-side len is stale (a retired occupant's, or
                # mid-prefill), and the step's masked write must not be
                # able to land in a page another row shares.
                tables = np.full(
                    (self.n_slots, self.row_pages), SCRATCH, np.int32
                )
                for idx in dec:
                    tables[idx] = slots[idx].table
                for idx in active_rows:
                    toks[idx] = slots[idx].last
                    active[idx] = True
                if self.governor is not None:
                    # Tally-style best-effort pacing: a sleep before the
                    # dispatch, never a skip — tokens stay bit-identical
                    self.governor.before_step()
                _step_t0 = time.perf_counter()
                lw = pool_lw(dec) if lora_on else ()
                if self.draft_params is not None:
                    # combined program: the draft model decodes the same
                    # token in the same dispatch so its pool never falls
                    # out of lockstep (the target subgraph and its
                    # argmax are unchanged — bit-identity holds)
                    nxt, self.cache, self.draft_cache = self._decode(
                        self.params, self.draft_params, jnp.asarray(toks),
                        self.cache, self.draft_cache, jnp.asarray(tables),
                        jnp.asarray(active), *lw,
                    )
                else:
                    nxt, self.cache = self._decode(
                        self.params, jnp.asarray(toks), self.cache,
                        jnp.asarray(tables), jnp.asarray(active), *lw,
                    )
                self.ticks += 1
                dispatched = True
                nxt = np.asarray(nxt)
                self.profiler.record(time.perf_counter() - _step_t0)
                for idx in active_rows:
                    s = slots[idx]
                    s.pos += 1
                    t = int(nxt[idx])
                    s.result.tokens.append(t)
                    s.last = t
                    if (
                        self.eos_id is not None and t == self.eos_id
                    ) or len(s.result.tokens) >= s.req.max_new:
                        retire(idx)

            if not dispatched:
                # Zero-progress iteration: every occupied row (and the
                # pending head) is page-starved. A radix drain cannot
                # help here — reaching this point means some try_pages
                # failed its freeable gate this iteration, and that gate
                # already counted everything a full drain could free —
                # so go straight to preempting the youngest row of ANY
                # tier, never the oldest, which therefore makes
                # monotonic progress and bounds the loop (the init
                # guarantee: one max_len row always fits the pool).
                occupied = [
                    (s.req.arrival, s.req.rid, idx)
                    for idx, s in enumerate(slots) if s.state != "free"
                ]
                protect = min(occupied)[2] if occupied else None
                if not preempt_one(critical_only=False, protect=protect):
                    raise RuntimeError(
                        "paged pool wedged: no dispatch possible, "
                        "no preemptable row — total_pages "
                        f"{self.total_pages} cannot make progress "
                        f"(free {self.allocator.free_pages})"
                    )

        self.publish_metrics()
        results.sort(key=lambda r: r.rid)
        # quiesced either way — a drain racing the run's natural end gets
        # the everything-retired answer (DrainHandshake.finish_run)
        self._drain.finish_run()
        return ServeStats(
            results=results, ticks=self.ticks,
            wall_s=time.perf_counter() - t0,
            trace_counts=dict(self.trace_counts),
            engine_cache=self.cache_stats(),
        )


# ---------------------------------------------------------------------------
# arrival drivers
# ---------------------------------------------------------------------------


def poisson_trace(
    n: int,
    *,
    seed: int,
    rate: float,
    vocab: int,
    prompt_lens: tuple[int, int],
    max_new: tuple[int, int] | Sequence[int],
    adapters: Sequence[str] | None = None,
) -> list[Request]:
    """Mixed-length Poisson arrival trace: exponential inter-arrival gaps
    at ``rate`` requests/tick, prompt lengths uniform over the (lo, hi)
    inclusive range. ``max_new`` as a TUPLE draws uniformly over the
    (lo, hi) range; a list draws from it as CHOICES — the
    serving-realistic bimodal mix (many short answers, a few long
    generations, e.g. ``[4, 4, 4, 40]``) that exposes lockstep's
    short-subsidizes-long waste. The type, not the length, disambiguates
    — a two-mode choices list like ``[4, 40]`` stays expressible.
    ``adapters`` assigns each request a LoRA adapter id drawn uniformly
    from the list (the multi-tenant mix; ``""`` entries mean the base
    model). Deterministic per seed — the replay driver is
    ``[Request(...)]`` literals."""
    if isinstance(max_new, tuple) and len(max_new) != 2:
        raise ValueError(
            f"max_new tuple must be (lo, hi), got {max_new!r}; pass a list "
            "for a choices mix"
        )
    rng = np.random.RandomState(seed)
    choices = None if isinstance(max_new, tuple) else list(max_new)
    t = 0.0
    out = []
    for rid in range(n):
        t += float(rng.exponential(1.0 / rate))
        plen = int(rng.randint(prompt_lens[0], prompt_lens[1] + 1))
        mn = (
            int(choices[rng.randint(len(choices))]) if choices is not None
            else int(rng.randint(max_new[0], max_new[1] + 1))
        )
        out.append(
            Request(
                rid=rid,
                prompt=tuple(int(x) for x in rng.randint(0, vocab, size=plen)),
                max_new=mn,
                arrival=t,
                adapter_id=(
                    "" if adapters is None
                    else str(adapters[rng.randint(len(adapters))])
                ),
            )
        )
    return out


def shared_prefix_trace(
    n: int,
    *,
    seed: int,
    rate: float,
    vocab: int,
    prefixes: tuple[int, int],
    tail_lens: tuple[int, int],
    max_new: tuple[int, int] | Sequence[int],
    tiers: Sequence[tuple[str, float, float | None, float | None]] | None = None,
    adapters: Sequence[str] | None = None,
) -> list[Request]:
    """Poisson arrivals whose prompts share system prompts: ``prefixes``
    is ``(count, length)`` — ``count`` distinct shared prefixes of
    ``length`` tokens are drawn once, and each request picks one
    uniformly and appends a unique tail of ``tail_lens`` (lo, hi)
    tokens. This is the radix-cache workload: every prefix past the
    first user prefills once and branches by reference-counted pages.

    ``tiers`` assigns SLO classes: a list of ``(tier_name, weight,
    slo_ttft_ticks, slo_tpot_ticks)`` rows sampled by weight — the
    targets ride on each :class:`Request` and are scored per tier in
    ``ServeStats.summary()``. None keeps every request
    :data:`TIER_CRITICAL` with no targets. ``max_new`` follows
    :func:`poisson_trace`'s tuple-range / choices-list convention;
    ``adapters`` assigns per-request LoRA adapter ids drawn uniformly
    (the multi-tenant mix — shared system prompts ACROSS tenants is
    exactly where paged adapters beat per-tenant engine forks, since the
    radix prefix pages stay shared while the deltas differ).
    Deterministic per seed."""
    n_pre, pre_len = prefixes
    if n_pre < 1 or pre_len < 0:
        raise ValueError(f"prefixes must be (count>=1, len>=0), got {prefixes}")
    rng = np.random.RandomState(seed)
    pres = [
        tuple(int(x) for x in rng.randint(0, vocab, size=pre_len))
        for _ in range(n_pre)
    ]
    choices = None if isinstance(max_new, tuple) else list(max_new)
    if tiers is not None:
        weights = np.asarray([t[1] for t in tiers], np.float64)
        weights = weights / weights.sum()
    t = 0.0
    out = []
    for rid in range(n):
        t += float(rng.exponential(1.0 / rate))
        pre = pres[rng.randint(n_pre)]
        tlen = int(rng.randint(tail_lens[0], tail_lens[1] + 1))
        tail = tuple(int(x) for x in rng.randint(0, vocab, size=tlen))
        mn = (
            int(choices[rng.randint(len(choices))]) if choices is not None
            else int(rng.randint(max_new[0], max_new[1] + 1))
        )
        tier, slo_ttft, slo_tpot = TIER_CRITICAL, None, None
        if tiers is not None:
            name, _, slo_ttft, slo_tpot = tiers[
                int(rng.choice(len(tiers), p=weights))
            ]
            tier = name
        out.append(Request(
            rid=rid, prompt=pre + tail, max_new=mn, arrival=t, tier=tier,
            slo_ttft_ticks=slo_ttft, slo_tpot_ticks=slo_tpot,
            adapter_id=(
                "" if adapters is None
                else str(adapters[rng.randint(len(adapters))])
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# static lockstep baseline
# ---------------------------------------------------------------------------


def run_static_baseline(
    params,
    cfg: TransformerConfig,
    requests: Sequence[Request],
    *,
    batch: int,
    eos_id: int | None = None,
    kv_dtype: str | None = None,
    warmup: bool = True,
    trials: int = 1,
) -> ServeStats:
    """The pre-engine serving discipline, instrumented for comparison:
    waves of up to ``batch`` requests run lockstep through ``generate()``
    (one padded prefill + ``max_new`` decode steps for EVERYONE), and
    nothing is admitted until the whole wave retires.

    Fair-but-generous accounting: a wave is taken the moment the pool is
    idle from whatever has ARRIVED (no waiting to fill the batch), the
    whole wave's prefill costs one tick (the engine pays one per chunk),
    and every wave decodes the GLOBAL max_new (lockstep cannot stop
    early — that is the point) at one tick per step. A member's tokens
    only exist when the batch call returns, so TTFT = wave completion −
    arrival on both clocks: the full-batch-lifetime TTFT the engine
    exists to fix. Tokens are truncated to each request's own
    ``max_new``/EOS so goodput counts the same useful tokens the engine
    produces (bit-identical, pinned by tests)."""
    gmax = max(r.max_new for r in requests)
    tp_max = max(len(r.prompt) for r in requests)
    gen = G.make_generate(
        cfg, max_new=gmax, eos_id=eos_id, padded=True, kv_dtype=kv_dtype
    )
    incoming = sorted(requests, key=lambda r: (r.arrival, r.rid))
    if warmup:  # compile off the clock, like SlotEngine.warmup
        np.asarray(gen(
            params, jnp.zeros((batch, tp_max), jnp.int32),
            jnp.ones((batch,), jnp.int32), jax.random.key(0),
        ))
    best: ServeStats | None = None
    for _ in range(max(1, trials)):
        results: list[RequestResult] = []
        tick = 0
        i = 0
        t0 = time.perf_counter()
        while i < len(incoming):
            if incoming[i].arrival > tick:
                tick = int(math.ceil(incoming[i].arrival))
            arrived = [r for r in incoming[i:] if r.arrival <= tick]
            wave = arrived[:batch]
            i += len(wave)
            # Fixed (batch, tp_max) shapes: one compile for the whole run.
            prompts = np.zeros((batch, tp_max), np.int32)
            lens = np.ones((batch,), np.int32)  # dummy rows: 1-token prompt
            for row, r in enumerate(wave):
                prompts[row, : len(r.prompt)] = r.prompt
                lens[row] = len(r.prompt)
            out = np.asarray(
                gen(params, jnp.asarray(prompts), jnp.asarray(lens),
                    jax.random.key(0))
            )
            tick += 1 + gmax  # one prefill tick + lockstep decode ticks
            wall = time.perf_counter() - t0
            for row, r in enumerate(wave):
                toks = [int(x) for x in out[row, : r.max_new]]
                if eos_id is not None and eos_id in toks:
                    toks = toks[: toks.index(eos_id) + 1]
                results.append(RequestResult(
                    rid=r.rid, prompt_len=len(r.prompt), tokens=toks,
                    arrival_tick=r.arrival,
                    first_token_tick=tick, finish_tick=tick,
                    first_token_s=wall, finish_s=wall,
                ))
        wall_total = time.perf_counter() - t0
        # Tick arrivals have no live wall analog in a lockstep run (tokens
        # only exist when a wave's batch call returns); convert them at the
        # run's measured seconds-per-tick so wall TTFT compares
        # like-for-like with the engine's live-observed arrivals.
        spt = wall_total / max(tick, 1)
        for res in results:
            res.arrival_s = min(res.arrival_tick * spt, res.first_token_s)
        results.sort(key=lambda r: r.rid)
        stats = ServeStats(
            results=results, ticks=tick, wall_s=wall_total, trace_counts={},
        )
        # Tokens/ticks are deterministic across trials; only wall time is
        # noisy — keep the best-of-N wall, like the bench's _timeit.
        if best is None or stats.wall_s < best.wall_s:
            best = stats
    return best


# ---------------------------------------------------------------------------
# slice-aware slot-pool sizing
# ---------------------------------------------------------------------------


def kv_slot_bytes(
    cfg: TransformerConfig, max_len: int, kv_dtype: str | None = None
) -> int:
    """HBM bytes one slot row pins: K+V across layers at ``max_len``
    positions (+ per-(token, head) scales for int8 caches)."""
    itemsize = 1 if kv_dtype == "int8" else jnp.dtype(cfg.compute_dtype).itemsize
    per = 2 * cfg.n_layers * max_len * cfg.kv_heads * cfg.head_dim * itemsize
    if kv_dtype == "int8":
        per += 2 * cfg.n_layers * max_len * cfg.kv_heads * 4  # f32 scales
    return per


def slots_for_slice(
    slice_bytes: int,
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
) -> int:
    """Slot-pool size a ``slice_bytes`` HBM slice sustains: weights come
    off the top, ``headroom`` covers activations + XLA workspace (the
    plugin's injected cap already shaves 5%, ``parallel/podenv.py``), and
    the rest divides by per-slot KV bytes. 0 means the slice cannot serve
    this config at all — callers must reject, not round up."""
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    usable = slice_bytes * headroom - weight_bytes
    if usable <= 0:
        return 0
    return int(usable // kv_slot_bytes(cfg, max_len, kv_dtype))


def slots_for_gang(
    per_chip_bytes: int,
    n_chips: int,
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
) -> int:
    """Slot-pool size a multi-chip gang sustains, computed over the
    PER-CHIP HBM shares: with the tensor-parallel engine each member chip
    pins ~``weight_bytes / n`` of the model and ``kv_slot_bytes / n`` per
    slot row (kv-heads shard over tp), so the binding constraint is one
    chip's share, not the gang total. When kv-heads do not divide by the
    gang size the cache replicates (``SlotEngine._shard_cache``) and the
    per-chip KV cost is the full row — sized here the same way so the
    estimate can never overshoot what the layout actually pins.
    0 means the gang cannot serve this config — callers reject."""
    if n_chips < 1:
        raise ValueError(f"n_chips must be >= 1, got {n_chips}")
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    per_slot = kv_slot_bytes(cfg, max_len, kv_dtype)
    if n_chips > 1 and cfg.kv_heads % n_chips == 0:
        per_slot_chip = -(-per_slot // n_chips)
        weights_chip = -(-weight_bytes // n_chips)
    else:
        per_slot_chip = per_slot
        weights_chip = weight_bytes
    usable = per_chip_bytes * headroom - weights_chip
    if usable <= 0:
        return 0
    return int(usable // per_slot_chip)


def slots_from_pod_env(
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    env: PodTpuEnv | None = None,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
    unit: MemoryUnit = MemoryUnit.GiB,
) -> int:
    """Slot pool for THIS pod's ``aliyun.com/tpu-mem`` slice, read from
    the plugin-injected env (:class:`~..parallel.podenv.PodTpuEnv`) — the
    closing of the loop: the device plugin carves the slice, the engine
    sizes its admission capacity to it. Multi-chip gangs size over their
    PER-CHIP shares (:func:`slots_for_gang`): the tensor-parallel pool is
    bounded by one member chip's slice, not the gang total. Raises when
    the slice cannot hold even one slot (a misconfigured pod should fail
    loudly at startup, not OOM mid-serve)."""
    pod = env if env is not None else PodTpuEnv.from_env()
    if pod.is_gang:
        # the CONTAINER's portion of the per-chip share: a multi-container
        # gang pod must not have every container size to the pod's whole
        # per-chip slice (they would jointly oversubscribe each chip)
        per_chip_bytes = pod.gang_container_per_chip_bytes(unit)
        n = slots_for_gang(
            per_chip_bytes, len(pod.gang_chips), cfg, max_len,
            weight_bytes=weight_bytes, kv_dtype=kv_dtype, headroom=headroom,
        )
        slice_desc = (
            f"gang slice of {per_chip_bytes / unit.num_bytes:g} "
            f"{unit.value}/chip x {len(pod.gang_chips)} chips"
        )
    else:
        n = slots_for_slice(
            pod.mem_bytes(unit), cfg, max_len,
            weight_bytes=weight_bytes, kv_dtype=kv_dtype, headroom=headroom,
        )
        slice_desc = f"slice of {pod.mem_units_container} {unit.value}"
    if n < 1:
        raise ValueError(
            f"{slice_desc} cannot hold "
            f"weights ({weight_bytes / 2**30:.2f} GiB) plus one "
            f"{max_len}-position KV slot "
            f"({kv_slot_bytes(cfg, max_len, kv_dtype) / 2**30:.3f} GiB) at "
            f"headroom {headroom} — request a larger aliyun.com/tpu-mem "
            "slice, shrink max_len, or quantize (kv_dtype='int8')"
        )
    return n


def paged_plan_from_pod_env(
    cfg: TransformerConfig,
    max_len: int,
    *,
    weight_bytes: int,
    page_size: int,
    prefill_chunk: int = 64,
    env: PodTpuEnv | None = None,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
    unit: MemoryUnit = MemoryUnit.GiB,
    slots: int | None = None,
    draft_cfg: TransformerConfig | None = None,
    draft_weight_bytes: int = 0,
) -> PagedPlan:
    """The paged mode of :func:`slots_from_pod_env`: size a
    :class:`PagedSlotEngine` pool (dispatch rows + KV pages) for THIS
    pod's ``aliyun.com/tpu-mem`` slice, read from the plugin-injected
    env. The page-table and free-list overhead is charged against the
    same byte budget, so a fully-admitted paged pool can never exceed
    the slice (the exact-budget accounting pinned in
    ``tests/test_pages_radix.py``). Gangs size over the container's
    PER-CHIP share with page bytes sharded on the kv-heads axis, exactly
    as :func:`slots_for_gang`. Raises when the slice cannot cover even
    one ``max_len`` row of pages — the paged engine's progress guarantee
    needs at least that many. ``draft_cfg``/``draft_weight_bytes``
    (speculative decoding) charge the draft model's weights and its
    per-page KV slab against the SAME slice budget — a spec engine asks
    for nothing beyond its ``aliyun.com/tpu-mem`` request."""
    pod = env if env is not None else PodTpuEnv.from_env()
    if pod.is_gang:
        per_chip_bytes = pod.gang_container_per_chip_bytes(unit)
        plan = paged_plan_for_slice(
            per_chip_bytes, cfg, max_len, page_size=page_size,
            prefill_chunk=prefill_chunk, weight_bytes=weight_bytes,
            kv_dtype=kv_dtype, headroom=headroom, slots=slots,
            n_chips=len(pod.gang_chips),
            draft_cfg=draft_cfg, draft_weight_bytes=draft_weight_bytes,
        )
        slice_desc = (
            f"gang slice of {per_chip_bytes / unit.num_bytes:g} "
            f"{unit.value}/chip x {len(pod.gang_chips)} chips"
        )
    else:
        plan = paged_plan_for_slice(
            pod.mem_bytes(unit), cfg, max_len, page_size=page_size,
            prefill_chunk=prefill_chunk, weight_bytes=weight_bytes,
            kv_dtype=kv_dtype, headroom=headroom, slots=slots,
            draft_cfg=draft_cfg, draft_weight_bytes=draft_weight_bytes,
        )
        slice_desc = f"slice of {pod.mem_units_container} {unit.value}"
    if plan.total_pages < pages_for(max_len, page_size):
        raise ValueError(
            f"{slice_desc} cannot hold weights "
            f"({weight_bytes / 2**30:.2f} GiB) plus one {max_len}-position "
            f"row of {page_size}-token KV pages at headroom {headroom} — "
            "request a larger aliyun.com/tpu-mem slice, shrink max_len, or "
            "quantize (kv_dtype='int8')"
        )
    return plan
