"""Best-effort step-rate governor: the reaction half of the
interference plane.

Tally (PAPERS.md 2410.07381) shows a best-effort tenant can share an
accelerator with a latency-critical one *non-intrusively*: never touch
the critical tenant, only pace the best-effort one's kernel launches
when the critical tenant is in danger. This module is that idea applied
to our serving engine's deterministic host loop: a **token bucket on
decode iterations**. The best-effort engine consults
:meth:`StepGovernor.before_step` once per decode dispatch; while the
governor is *engaged*, iterations drain tokens refilled at
``throttled_steps_per_s`` and a dry bucket sleeps the host loop until
the next token accrues. While *released*, ``before_step`` is two loads
and a compare — the engine runs at full rate.

Engage/release policy (driven by the SLO burn-rate signal,
``utils/slo.py``):

- **engage** the moment ``burn_fn()`` reports page severity for the
  co-resident latency-critical tier (one poll per
  ``poll_interval_steps`` iterations — the signal source holds a lock,
  so it must be off the per-step path);
- **release hysteretically**: only after ``release_after`` consecutive
  clean polls — a budget that flaps around the page threshold must not
  turn the throttle into an oscillator.

Every transition is observable: a ``governor.engage``/``governor.release``
span (with the triggering severity and the engaged duration), the
``tpushare_governor_engagements_total`` counter, the
``tpushare_governor_engaged{pod}`` gauge, and
``tpushare_governor_throttle_seconds_total`` accumulating the imposed
sleep — the reaction itself shows up in ``/metrics`` and ``/traces``,
not just its effect.

Correctness bar: the governor only ever *delays* dispatches, never
reorders, drops, or alters them — greedy tokens stay bit-identical and
the 3-compiled-programs invariant is untouched (gated hard in
``bench_mfu.py --interference-smoke``). State is engine-thread-only by
design (no lock): ``burn_fn`` crosses threads, the governor does not.
"""

from __future__ import annotations

import time
from typing import Callable

from ..utils.log import get_logger
from ..utils.metrics import MetricsRegistry, REGISTRY
from ..utils.tracing import TRACER
from ..utils.metric_catalog import (
    GOVERNOR_ENGAGED as ENGAGED_GAUGE,
    GOVERNOR_ENGAGEMENTS_TOTAL as ENGAGEMENTS_TOTAL,
    GOVERNOR_THROTTLED_STEPS_TOTAL as THROTTLED_STEPS_TOTAL,
    GOVERNOR_THROTTLE_SECONDS_TOTAL as THROTTLE_SECONDS_TOTAL,
)

log = get_logger("serving.governor")



class StepGovernor:
    """Token-bucket throttle on a best-effort engine's decode iterations.

    ``burn_fn() -> str | None`` returns the co-resident critical tier's
    current burn severity (``utils.slo.SloBudget.severity``, or any
    callable — the interference detector's verdict works too); ``"page"``
    engages. ``clock``/``sleep`` are injectable so tests and the
    deterministic bench can drive the bucket without real waiting.
    """

    def __init__(
        self,
        burn_fn: Callable[[], str | None],
        *,
        throttled_steps_per_s: float = 20.0,
        burst: float = 2.0,
        poll_interval_steps: int = 8,
        release_after: int = 3,
        engage_on: str = "page",
        pod: str = "",
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if throttled_steps_per_s <= 0:
            raise ValueError(
                f"throttled_steps_per_s must be > 0, got {throttled_steps_per_s}"
            )
        if burst <= 0:
            raise ValueError(f"burst must be > 0, got {burst}")
        if poll_interval_steps < 1:
            raise ValueError(
                f"poll_interval_steps must be >= 1, got {poll_interval_steps}"
            )
        if release_after < 1:
            raise ValueError(f"release_after must be >= 1, got {release_after}")
        self._burn_fn = burn_fn
        self._rate = throttled_steps_per_s
        # burst is the bucket CAP, not a minimum: a cap below 1.0 means
        # the bucket can never hold a full token, so an engaged engine
        # pays a wait before EVERY dispatch — idle gaps (a drained run,
        # a quiet queue) cannot accrue a "free" dispatch that lands as
        # a contention spike the moment work resumes
        self._burst = float(burst)
        self._poll_every = poll_interval_steps
        self._release_after = release_after
        # "page" engages on page only; "warn" engages on warn OR page
        self._engage_on = engage_on
        self._pod = pod
        self._reg = registry if registry is not None else REGISTRY
        self._clock = clock
        self._sleep = sleep
        self.engaged = False
        self.engagements = 0
        self.throttled_steps = 0
        self.throttle_seconds = 0.0
        self._steps_since_poll = 0
        self._clean_polls = 0
        self._tokens = self._burst
        self._last_refill = clock()
        self._engaged_at = 0.0
        self._last_severity: str | None = None

    # --- policy -----------------------------------------------------------

    def _severity_engages(self, severity: str | None) -> bool:
        if severity is None:
            return False
        if self._engage_on == "warn":
            return severity in ("warn", "page")
        return severity == "page"

    def _labels(self) -> dict[str, str]:
        return {"pod": self._pod} if self._pod else {}

    def _engage(self, severity: str) -> None:
        self.engaged = True
        self.engagements += 1
        self._clean_polls = 0
        # the bucket starts EMPTY: the victim is burning right now, so a
        # freshly-engaged governor pauses immediately instead of
        # spending a burst of free dispatches into the contention
        self._tokens = 0.0
        self._last_refill = self._clock()
        self._engaged_at = self._last_refill
        labels = self._labels()
        self._reg.counter_inc(
            ENGAGEMENTS_TOTAL,
            "Times the best-effort governor engaged its step throttle",
            **labels,
        )
        self._reg.gauge_set(
            ENGAGED_GAUGE, 1.0,
            "Whether the best-effort step throttle is currently engaged",
            **labels,
        )
        with TRACER.span(
            "governor.engage",
            attributes={
                "severity": severity, "pod": self._pod,
                "throttled_steps_per_s": self._rate,
            },
        ):
            pass
        log.info(
            "governor engaged (severity=%s): best-effort decode throttled "
            "to %.1f steps/s", severity, self._rate,
        )

    def _release(self) -> None:
        engaged_s = self._clock() - self._engaged_at
        self.engaged = False
        self._reg.gauge_set(
            ENGAGED_GAUGE, 0.0,
            "Whether the best-effort step throttle is currently engaged",
            **self._labels(),
        )
        with TRACER.span(
            "governor.release",
            attributes={"pod": self._pod, "engaged_s": round(engaged_s, 3)},
        ):
            pass
        log.info(
            "governor released after %.2fs (%d clean polls)",
            engaged_s, self._release_after,
        )

    def poll(self) -> None:
        """Re-read the burn signal and update the engage state (also
        called internally every ``poll_interval_steps`` iterations)."""
        severity = self._burn_fn()
        self._last_severity = severity
        if self._severity_engages(severity):
            self._clean_polls = 0
            if not self.engaged:
                self._engage(severity or "")
        elif self.engaged:
            self._clean_polls += 1
            if self._clean_polls >= self._release_after:
                self._release()

    # --- the hot-path hook --------------------------------------------------

    def before_step(self) -> float:
        """Called by the engine once per decode iteration. Returns the
        seconds slept (0.0 on the unthrottled fast path). Never raises,
        never skips the step — it only delays it."""
        self._steps_since_poll += 1
        if self._steps_since_poll >= self._poll_every:
            self._steps_since_poll = 0
            self.poll()
        if not self.engaged:
            return 0.0
        now = self._clock()
        self._tokens = min(
            self._burst, self._tokens + (now - self._last_refill) * self._rate
        )
        self._last_refill = now
        slept = 0.0
        if self._tokens < 1.0:
            wait = (1.0 - self._tokens) / self._rate
            self._sleep(wait)
            slept = wait
            now = self._clock()
            self._tokens = min(
                self._burst,
                self._tokens + (now - self._last_refill) * self._rate,
            )
            self._last_refill = now
            self.throttled_steps += 1
            self.throttle_seconds += slept
            labels = self._labels()
            self._reg.counter_inc(
                THROTTLED_STEPS_TOTAL,
                "Decode iterations delayed by the best-effort governor",
                **labels,
            )
            self._reg.counter_inc(
                THROTTLE_SECONDS_TOTAL,
                "Cumulative seconds of governor-imposed decode delay",
                value=slept, **labels,
            )
        self._tokens = max(0.0, self._tokens - 1.0)
        return slept

    def stats(self) -> dict[str, float | int | bool | None]:
        """Telemetry snapshot (bench/report row)."""
        return {
            "engaged": self.engaged,
            "engagements": self.engagements,
            "throttled_steps": self.throttled_steps,
            "throttle_seconds": round(self.throttle_seconds, 4),
            "last_severity": self._last_severity,
        }
