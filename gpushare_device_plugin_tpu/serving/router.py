"""Fleet front door: routing, membership, and journaled scale-down.

One engine serves one slice; millions of users need a POOL of engines
behind a router that survives engine crashes, stale telemetry, and
capacity swings without dropping a request — the "loses latency, never
requests" contract PR 16's handoff ladder established, lifted to the
fleet. This module is the jax-free half (like ``handoffproto.py``): the
routing table, the failure detector, and the journaled scale-down
protocol, free of engine state so ``tools/tpumc`` can enumerate the
protocol's interleavings and the chaos suite can SIGKILL it at every
journal step (``make chaos-fleet``). The engine-facing binding lives in
``serving/fleet.py``.

Four pieces:

- :class:`FleetRouter` — scores every ready replica through the PR 13
  policy registry (default ``prefix-affinity``: radix-fingerprint
  overlap tempered by headroom) and emits a PR 12 DecisionRecord per
  route/shed, so ``inspect why`` explains fleet routing exactly the way
  it explains placement. Prefix affinity degrades to load balancing
  when fingerprints are stale or a scrape failed — affinity is a
  performance signal, never a correctness dependency.
- :class:`FleetMembership` — health-checked replica table: each member
  is scraped through an :class:`EngineScrapeClient` (``utils/retry.py``
  backoff over a ``utils/circuit.py`` breaker, the handoff peer's
  discipline), consecutive misses evict, the prefix fingerprints ride
  the same scrape.
- SLO-aware shedding — the router reads PR 11's burn-rate severity and
  queue depths and degrades BEST-EFFORT traffic first; critical
  requests are routed (or queued on the least-loaded replica) as long
  as one replica lives.
- The **scale** protocol — scale-down is WAL record kind ``"scale"``
  journaled through ``cordon -> drain -> migrate -> release``, each
  record durable *before* its side effect (the move/handoff template):

  - **cordon**: intent durable, then the replica closes to new routes —
    its in-flight row set is frozen from here.
  - **drain**: the frozen request rows are durable (the re-prefill
    guarantee: from here a crash can re-serve every in-flight request
    from the journal alone), then the engine drains to a KV snapshot.
  - **migrate**: the **commit point**. The drained snapshot is durable,
    then a survivor adopts it (idempotent by ``snapshot_id`` — the
    restore dedup discipline). At or past this phase a crash rolls
    FORWARD (re-deliver); before it, a crash rolls BACK (re-queue the
    journaled rows on survivors, full re-prefill, tokens bit-identical
    by greedy determinism).
  - **release**: decommission intent durable, then the replica leaves
    the membership; the WAL entry resolves.

  :func:`resolve_scale` is the reconciler's roll-forward/roll-back
  hook, same shape as ``resolve_handoff``. SIGKILL at any phase loses
  latency, never a request — ``tests/test_fleet.py`` pins every site.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Mapping

from ..allocator.checkpoint import AllocationCheckpoint, StaleDaemonError
from ..const import (
    FLEET_REPLICA_CORDONED,
    FLEET_REPLICA_DEAD,
    FLEET_REPLICA_READY,
    FLEET_REPLICA_STATES,
    SLO_TIER_BEST_EFFORT,
    SLO_TIER_CRITICAL,
)
from ..extender.policy import PolicyView
from ..extender.policy import resolve as resolve_policy
from ..utils.circuit import CircuitBreaker, CircuitOpenError
from ..utils.decisions import DECISIONS, DecisionLog, rank_scores
from ..utils.faults import FAULTS
from ..utils.lockrank import make_lock
from ..utils.log import get_logger
from ..utils.metric_catalog import (
    FLEET_DRAIN_MIGRATED_REQUESTS_TOTAL,
    FLEET_REPLICAS,
    FLEET_SCALE_OPS_TOTAL,
    ROUTER_PREFIX_AFFINITY_HITS_TOTAL,
    ROUTER_ROUTED_TOTAL,
    ROUTER_SHED_TOTAL,
)
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.retry import retry
from ..utils.slo import SEVERITY_PAGE, SloBudget
from .radix import prefix_fingerprints

log = get_logger("serving.router")

# The journaled scale-down state machine, in order. Each phase's WAL
# record is durable BEFORE its side effect; "migrate" is the
# roll-forward boundary (the analogue of handoff's "import").
SCALE_PHASES = ("cordon", "drain", "migrate", "release")
SCALE_KIND = "scale"
SCALE_ROLL_FORWARD_PHASES = ("migrate", "release")

# Synthetic namespace for scale journal keys, like HANDOFF_NS: the
# entry is keyed by scale-op id, never mistaken for a real pod's own
# accounting.
SCALE_NS = "tpushare-scale"

ROUTED_HELP = "Requests routed by the fleet router, by engine and outcome"
AFFINITY_HELP = (
    "Routes landing on an engine already holding the prompt prefix"
)
SHED_HELP = (
    "Requests shed at admission by SLO tier (best-effort degrades first)"
)
REPLICAS_HELP = "Fleet replicas by lifecycle state"
MIGRATED_HELP = (
    "In-flight requests migrated to a survivor by scale-down drains"
)
SCALE_OPS_HELP = "Journaled scale-down protocol executions by outcome"


def scale_key(scale_id: str) -> tuple[str, str]:
    """The journal key for one scale-down operation (synthetic ns)."""
    return (SCALE_NS, scale_id)


def _journal_scale(
    ckpt: AllocationCheckpoint | None, key: tuple[str, str], data: dict
) -> int | None:
    """Journal one scale phase durable (a fresh ``begin`` for the scale
    key — the loader keeps the newest record per key, so the entry
    always names the furthest phase reached, exactly like
    ``_journal_handoff``). ``StaleDaemonError`` propagates: a fenced
    daemon must not advance a scale-down the newer incarnation owns.
    ``None`` = journal degraded (sick disk): the scale-down continues
    unjournaled, like admissions do. (tpulint's wal-protocol rule knows
    this helper as a ``begin`` form — every call site must be dominated
    by :func:`_journal_resolve` on its handled paths.)"""
    if ckpt is None:
        return None
    return ckpt.begin(key, data)


def _journal_resolve(
    ckpt: AllocationCheckpoint | None,
    op: str,
    key: tuple[str, str],
    seq: int | None,
) -> bool:
    """Resolve the scale entry (``op`` = ``"commit"`` the replica was
    drained/migrated/released, ``"abort"`` the scale-down rolled back);
    the thin delegation form the wal-protocol rule recognizes. False =
    degraded/unjournaled or a newer begin owns the key."""
    if ckpt is None:
        return False
    if op == "commit":
        return ckpt.commit(key, seq=seq)
    return ckpt.abort(key, seq=seq)


# ---------------------------------------------------------------------------
# health-checked membership
# ---------------------------------------------------------------------------


class EngineScrapeClient:
    """One replica's heartbeat path: ``scrape_fn() -> doc`` retried with
    exponential backoff under a per-call deadline, behind a circuit
    breaker so a dead replica fails fast instead of serializing full
    retry ladders into every membership pass. Stateless apart from the
    breaker — miss counting lives in :class:`FleetMembership` (one
    owner for eviction state), so this class needs no lock of its own.

    The doc contract (what ``serving/fleet.py`` exports per engine and
    the /fleet endpoint re-serves): ``free_slots``, ``capacity``,
    ``queue_depth``, and ``fingerprints`` — the radix cache's chained
    page-path CRCs (:meth:`~.radix.RadixCache.fingerprints`)."""

    def __init__(
        self,
        scrape_fn: Callable[[], Mapping[str, Any]],
        *,
        attempts: int = 2,
        delay_s: float = 0.01,
        backoff: float = 2.0,
        deadline_s: float = 1.0,
        breaker: CircuitBreaker | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._fn = scrape_fn
        self._attempts = attempts
        self._delay = delay_s
        self._backoff = backoff
        self._deadline = deadline_s
        self._breaker = breaker or CircuitBreaker(
            "fleet-scrape", failure_threshold=5, reset_timeout_s=1.0,
            clock=clock,
        )
        self._sleep = sleep
        self._clock = clock

    def scrape(self) -> dict[str, Any]:
        def once() -> dict[str, Any]:
            self._breaker.before()
            try:
                out = dict(self._fn())
            except Exception:
                self._breaker.record_failure()
                raise
            self._breaker.record_success()
            return out

        return retry(
            once,
            attempts=self._attempts,
            delay_s=self._delay,
            backoff=self._backoff,
            deadline_s=self._deadline,
            # an OPEN breaker is a fail-fast verdict, not a blip
            retryable=lambda e: not isinstance(e, CircuitOpenError),
            sleep=self._sleep,
            clock=self._clock,
        )


@dataclasses.dataclass(frozen=True)
class MemberView:
    """One replica as the router sees it (an immutable snapshot — the
    route decision never reads the live table twice)."""

    name: str
    state: str
    fingerprints: frozenset[int]
    free_slots: int
    capacity: int
    queue_depth: int


@dataclasses.dataclass
class _Member:
    client: EngineScrapeClient | None
    state: str = FLEET_REPLICA_READY
    misses: int = 0
    fingerprints: set[int] = dataclasses.field(default_factory=set)
    free_slots: int = 0
    capacity: int = 0
    queue_depth: int = 0


class FleetMembership:
    """The fleet's replica table: health, cordon flags, scraped load and
    prefix fingerprints. Failure detection is consecutive-miss eviction:
    a replica whose scrape fails ``miss_threshold`` times in a row is
    marked dead (the router stops considering it; the fleet binding
    re-queues its in-flight requests on survivors).

    Thread-safe under rank ``fleet.membership`` — held around table
    flips only, never across a scrape transport call or its breaker.
    """

    def __init__(
        self,
        *,
        miss_threshold: int = 3,
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        if miss_threshold < 1:
            raise ValueError(
                f"miss_threshold must be >= 1, got {miss_threshold}"
            )
        self._lock = make_lock("fleet.membership")
        self._members: dict[str, _Member] = {}
        self._miss_threshold = miss_threshold
        self._registry = registry
        self._pod = pod

    def add(
        self,
        name: str,
        client: EngineScrapeClient | None = None,
        *,
        capacity: int = 0,
        free_slots: int | None = None,
    ) -> None:
        """Register a replica (scale-up / bootstrap). Capacity seeds the
        router until the first scrape refreshes it."""
        with self._lock:
            self._members[name] = _Member(
                client=client,
                capacity=capacity,
                free_slots=capacity if free_slots is None else free_slots,
            )

    def remove(self, name: str) -> None:
        with self._lock:
            self._members.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def set_state(self, name: str, state: str) -> None:
        if state not in FLEET_REPLICA_STATES:
            raise ValueError(
                f"state {state!r} not in {FLEET_REPLICA_STATES}"
            )
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.state = state

    def cordon(self, name: str) -> None:
        """Close a replica to new routes (scale-down's first durable
        step, or an operator's manual drain)."""
        self.set_state(name, FLEET_REPLICA_CORDONED)

    def uncordon(self, name: str) -> None:
        """Re-open a cordoned replica (scale-down rollback)."""
        self.set_state(name, FLEET_REPLICA_READY)

    def mark_dead(self, name: str) -> None:
        self.set_state(name, FLEET_REPLICA_DEAD)

    def note_routed(self, name: str, fingerprints: list[int]) -> None:
        """Optimistically credit a replica with the prefix pages it is
        ABOUT to cache for a request just routed there: affinity then
        works within one scrape interval (the next scrape replaces the
        estimate with the engine's exported truth)."""
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.fingerprints.update(fingerprints)

    def scrape_once(self) -> dict[str, bool]:
        """One heartbeat pass: scrape every replica that has a client,
        transport OUTSIDE the lock, table flips under it. Returns
        name -> scrape-succeeded; a replica reaching the consecutive-
        miss threshold flips to dead (eviction)."""
        with self._lock:
            targets = [
                (name, m.client)
                for name, m in self._members.items()
                if m.client is not None
                and m.state != FLEET_REPLICA_DEAD
            ]
        outcomes: dict[str, bool] = {}
        for name, client in targets:
            doc: dict[str, Any] | None
            try:
                doc = client.scrape()
            except Exception as e:  # noqa: BLE001 — a miss, not a bug
                doc = None
                log.v(4, "fleet scrape of %s failed: %s", name, e)
            with self._lock:
                m = self._members.get(name)
                if m is None:
                    continue
                if doc is None:
                    m.misses += 1
                    outcomes[name] = False
                    if (
                        m.misses >= self._miss_threshold
                        and m.state != FLEET_REPLICA_DEAD
                    ):
                        m.state = FLEET_REPLICA_DEAD
                        log.warning(
                            "fleet replica %s evicted after %d "
                            "consecutive scrape misses", name, m.misses,
                        )
                else:
                    m.misses = 0
                    m.free_slots = int(doc.get("free_slots", m.free_slots))
                    m.capacity = int(doc.get("capacity", m.capacity))
                    m.queue_depth = int(
                        doc.get("queue_depth", m.queue_depth)
                    )
                    fps = doc.get("fingerprints")
                    if fps is not None:
                        m.fingerprints = {int(f) for f in fps}
                    outcomes[name] = True
        return outcomes

    def snapshot(self) -> list[MemberView]:
        with self._lock:
            return [
                MemberView(
                    name=name,
                    state=m.state,
                    fingerprints=frozenset(m.fingerprints),
                    free_slots=m.free_slots,
                    capacity=m.capacity,
                    queue_depth=m.queue_depth,
                )
                for name, m in sorted(self._members.items())
            ]

    def publish(self) -> None:
        with self._lock:
            counts = {state: 0 for state in FLEET_REPLICA_STATES}
            for m in self._members.values():
                counts[m.state] = counts.get(m.state, 0) + 1
        labels = {"pod": self._pod} if self._pod else {}
        for state, n in counts.items():
            self._registry.gauge_set(
                FLEET_REPLICAS, float(n), REPLICAS_HELP, state=state,
                **labels,
            )

    def doc(self) -> dict[str, Any]:
        with self._lock:
            return {
                "replicas": {
                    name: {
                        "state": m.state,
                        "misses": m.misses,
                        "free_slots": m.free_slots,
                        "capacity": m.capacity,
                        "queue_depth": m.queue_depth,
                        "fingerprints": len(m.fingerprints),
                    }
                    for name, m in sorted(self._members.items())
                },
            }


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RouteDecision:
    """One admission verdict. ``engine`` is None when the request was
    shed (best-effort under SLO pressure) or no replica is ready; the
    caller queues or rejects accordingly — the router never silently
    drops."""

    rid: str
    engine: str | None
    outcome: str
    reason: str
    affinity_pages: int = 0

    @property
    def shed(self) -> bool:
        return self.outcome == "shed"


class FleetRouter:
    """Scores ready replicas per request and owns the in-flight
    routing table (rid -> engine), so an engine death can re-queue
    exactly its in-flight set on survivors.

    Lock discipline (rank ``fleet.router``): the SLO severity read
    (rank 64) and the membership snapshot (rank 77... taken while NOT
    holding this lock) happen before acquisition; DecisionRecord
    emission (rank 65) and metric counters happen after release. The
    lock guards only the assignment table and counters."""

    def __init__(
        self,
        membership: FleetMembership,
        *,
        page_size: int,
        policy: "str | PlacementPolicy" = "prefix-affinity",
        slo_budget: SloBudget | None = None,
        shed_queue_depth: int = 64,
        decisions: DecisionLog = DECISIONS,
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._membership = membership
        self._page_size = page_size
        self._policy = resolve_policy(policy)
        self._slo = slo_budget
        self._shed_queue_depth = shed_queue_depth
        self._decisions = decisions
        self._registry = registry
        self._pod = pod
        self._lock = make_lock("fleet.router")
        self._inflight: dict[str, str] = {}
        self._assigned: dict[str, int] = {}
        self._counts: dict[str, int] = {}
        self._affinity_hits = 0

    @staticmethod
    def _affinity(fps: list[int], member: frozenset[int]) -> int:
        """Consecutive prefix pages the member already caches. The
        fingerprints are CRC-chained (each commits to the whole path),
        so membership of ``fps[i]`` implies the engine holds pages
        ``0..i`` of THIS prompt — overlap is counted from the front and
        stops at the first miss."""
        pages = 0
        for fp in fps:
            if fp not in member:
                break
            pages += 1
        return pages

    def route(
        self,
        rid: str,
        prompt: tuple[int, ...],
        tier: str = SLO_TIER_CRITICAL,
    ) -> RouteDecision:
        """Admit one request: pick an engine (affinity- and headroom-
        scored through the policy registry), shed it (best-effort under
        SLO pressure), or report no replica is ready. Exactly one
        DecisionRecord is emitted per call, whatever the outcome."""
        # Down-rank reads FIRST: slo.budget (64) sits below fleet.router.
        severity = (
            self._slo.severity(SLO_TIER_CRITICAL)
            if self._slo is not None
            else None
        )
        members = self._membership.snapshot()
        ready = [m for m in members if m.state == FLEET_REPLICA_READY]
        fps = prefix_fingerprints(tuple(prompt), self._page_size)
        engine: str | None = None
        pages = 0
        scores: dict[str, Any] | None = None
        with self._lock:
            load = {
                m.name: self._assigned.get(m.name, 0) for m in ready
            }
            if not ready:
                outcome, reason = "no_replicas", "no ready replicas"
            elif (
                tier == SLO_TIER_BEST_EFFORT
                and severity == SEVERITY_PAGE
            ):
                outcome = "shed"
                reason = (
                    "critical-tier burn rate at page severity; "
                    "best-effort degrades first"
                )
            elif tier == SLO_TIER_BEST_EFFORT and all(
                m.queue_depth + load[m.name] >= self._shed_queue_depth
                for m in ready
            ):
                outcome = "shed"
                reason = (
                    f"every replica queue >= {self._shed_queue_depth}; "
                    "best-effort degrades first"
                )
            else:
                scores = {}
                affinity = {}
                for m in ready:
                    affinity[m.name] = self._affinity(
                        fps, m.fingerprints
                    )
                    scores[m.name] = self._policy.score(
                        PolicyView(
                            free_units=max(
                                0, m.free_slots - load[m.name]
                            ),
                            capacity=max(1, m.capacity),
                            request_units=1,
                            affinity_pages=affinity[m.name],
                        )
                    )
                best = rank_scores(scores)[0]
                if scores[best].raw <= 0.0:
                    # every replica is saturated: queue on the least
                    # loaded one rather than drop — queue-depth
                    # balancing is the floor, shedding is tier-gated
                    best = min(
                        ready,
                        key=lambda m: (
                            m.queue_depth + load[m.name], m.name
                        ),
                    ).name
                    outcome = "overflow"
                    reason = "no headroom anywhere; queued least-loaded"
                elif affinity[best] > 0:
                    outcome = "affinity"
                    reason = (
                        f"{affinity[best]} prefix pages warm on {best}"
                    )
                else:
                    outcome = "balanced"
                    reason = f"load-balanced onto {best}"
                engine = best
                pages = affinity.get(best, 0)
                self._inflight[rid] = engine
                self._assigned[engine] = load.get(engine, 0) + 1
                if pages > 0:
                    self._affinity_hits += 1
            self._counts[outcome] = self._counts.get(outcome, 0) + 1
        # Down-rank side effects AFTER release: decisions.ring (65).
        verb = "fleet_shed" if outcome == "shed" else "fleet_route"
        self._decisions.emit(
            rid, verb, outcome=outcome, node=engine or "",
            reason=reason, candidates=len(ready), scores=scores,
        )
        labels = {"pod": self._pod} if self._pod else {}
        if outcome == "shed":
            self._registry.counter_inc(
                ROUTER_SHED_TOTAL, SHED_HELP, tier=tier, **labels
            )
        else:
            self._registry.counter_inc(
                ROUTER_ROUTED_TOTAL, ROUTED_HELP,
                engine=engine or "none", outcome=outcome, **labels,
            )
        if pages > 0:
            self._registry.counter_inc(
                ROUTER_PREFIX_AFFINITY_HITS_TOTAL, AFFINITY_HELP,
                **labels,
            )
        if engine is not None and fps:
            self._membership.note_routed(engine, fps)
        return RouteDecision(
            rid=rid, engine=engine, outcome=outcome, reason=reason,
            affinity_pages=pages,
        )

    def complete(self, rid: str) -> None:
        """A routed request finished (served, or re-queued elsewhere)."""
        with self._lock:
            engine = self._inflight.pop(rid, None)
            if engine is not None:
                n = self._assigned.get(engine, 0) - 1
                if n > 0:
                    self._assigned[engine] = n
                else:
                    self._assigned.pop(engine, None)

    def inflight_on(self, engine: str) -> list[str]:
        with self._lock:
            return sorted(
                rid for rid, e in self._inflight.items() if e == engine
            )

    def forget_engine(self, engine: str) -> list[str]:
        """Drop an engine's whole in-flight set (it died, or its drain
        snapshot migrated) and return the rids — the fleet binding
        re-queues them on survivors."""
        with self._lock:
            rids = sorted(
                rid for rid, e in self._inflight.items() if e == engine
            )
            for rid in rids:
                del self._inflight[rid]
            self._assigned.pop(engine, None)
            return rids

    def least_loaded(
        self, exclude: "frozenset[str] | set[str]" = frozenset()
    ) -> str | None:
        """The ready replica with the shallowest queue (scraped depth +
        this router's live assignments) — the migrate hook's survivor
        pick and the overflow floor share this definition. None when no
        ready replica remains."""
        ready = [
            m for m in self._membership.snapshot()
            if m.state == FLEET_REPLICA_READY and m.name not in exclude
        ]
        if not ready:
            return None
        with self._lock:
            return min(
                ready,
                key=lambda m: (
                    m.queue_depth + self._assigned.get(m.name, 0),
                    m.name,
                ),
            ).name

    def seed_inflight(self, assignments: Mapping[str, str]) -> None:
        """Rebuild the routing table after a router restart from the
        engines' own in-flight docs (the engines are the ground truth —
        the router's table is a cache of it)."""
        with self._lock:
            for rid, engine in assignments.items():
                if rid not in self._inflight:
                    self._inflight[rid] = engine
                    self._assigned[engine] = (
                        self._assigned.get(engine, 0) + 1
                    )

    def doc(self) -> dict[str, Any]:
        with self._lock:
            routed = sum(
                n for o, n in self._counts.items() if o != "shed"
            )
            return {
                "policy": self._policy.name,
                "outcomes": dict(sorted(self._counts.items())),
                "inflight": len(self._inflight),
                "affinity_hits": self._affinity_hits,
                "affinity_hit_ratio": (
                    self._affinity_hits / routed if routed else 0.0
                ),
            }


# ---------------------------------------------------------------------------
# the journaled scale-down executor
# ---------------------------------------------------------------------------


class ScaleExecutor:
    """Executes one scale-down through the journaled protocol.

    The side effects are bindings the fleet provides: ``cordon_fn``
    closes the replica to new routes, ``rows_fn`` reads its frozen
    in-flight request rows (JSON-safe, post-cordon), ``drain_fn`` runs
    the engine to its drain snapshot, ``migrate_fn(snapshot, record)``
    delivers the snapshot to a survivor (idempotent by snapshot_id)
    and returns how many requests moved, ``release_fn`` decommissions
    the replica. Exceptions out of :meth:`execute` leave the journal
    entry pending for the reconciler — deliberately: that IS the
    crash-safety story, same as the defrag and handoff movers.

    Lock discipline (rank ``fleet.scale``): held for counter flips
    only — never across a journal write (rank 40) or an engine call
    (rank 89)."""

    def __init__(
        self,
        checkpoint: AllocationCheckpoint | None,
        assume: Any,
        *,
        cordon_fn: Callable[[str], None],
        rows_fn: Callable[[str], list[dict]],
        drain_fn: Callable[[str], dict],
        migrate_fn: Callable[[dict, dict], int],
        release_fn: Callable[[str], None],
        node: str = "",
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        self._ckpt = checkpoint
        self._assume = assume
        self._cordon = cordon_fn
        self._rows = rows_fn
        self._drain = drain_fn
        self._migrate = migrate_fn
        self._release = release_fn
        self._node = node
        self._registry = registry
        self._pod = pod
        self._lock = make_lock("fleet.scale")
        self.migrated_requests = 0
        self.completed_ops = 0

    def _count(self, outcome: str) -> None:
        labels = {"pod": self._pod} if self._pod else {}
        self._registry.counter_inc(
            FLEET_SCALE_OPS_TOTAL, SCALE_OPS_HELP, outcome=outcome,
            **labels,
        )

    def execute(self, scale_id: str, engine: str) -> str:
        """Scale one replica down end to end: ``"scaled"`` (drained,
        migrated, released) or ``"skipped"`` (a concurrent executor owns
        the op). Raises when a side effect fails: the entry stays
        pending and the reconciler rolls it forward or back — the
        in-flight requests are delayed, never lost."""
        key = scale_key(scale_id)
        if self._assume is not None and not self._assume.claim(key):
            log.v(4, "scale %s already in flight; skipped", scale_id)
            return "skipped"
        base = {
            "kind": SCALE_KIND,
            "scale_id": scale_id,
            "engine": engine,
            "node": self._node,
        }
        try:
            # cordon: intent durable, then the replica closes to new
            # routes — the in-flight row set is frozen from here.
            seq = _journal_scale(self._ckpt, key, {**base, "phase": "cordon"})
            FAULTS.fire("scale.cordon")
            self._cordon(engine)
            # drain: the frozen rows are durable BEFORE the engine
            # drains — from here a crash can re-serve every in-flight
            # request from the journal alone (full re-prefill on a
            # survivor, tokens bit-identical by greedy determinism).
            rows = [dict(r) for r in self._rows(engine)]
            seq = _journal_scale(
                self._ckpt, key, {**base, "phase": "drain", "rows": rows}
            )
            FAULTS.fire("scale.drain")
            snapshot = self._drain(engine)
            # migrate: the commit point — the drained snapshot is
            # durable, then a survivor adopts it (idempotent by
            # snapshot_id). At or past this record a crash rolls
            # forward.
            seq = _journal_scale(
                self._ckpt, key,
                {**base, "phase": "migrate", "rows": rows,
                 "snapshot": snapshot},
            )
            FAULTS.fire("scale.migrate")
            moved = int(self._migrate(snapshot, dict(base)))
            # release: decommission intent durable, then the replica
            # leaves the membership; the entry resolves.
            seq = _journal_scale(
                self._ckpt, key, {**base, "phase": "release"}
            )
            FAULTS.fire("scale.release")
            self._release(engine)
            _journal_resolve(self._ckpt, "commit", key, seq)
            self._release_claim(key)
        except StaleDaemonError:
            # a newer daemon fenced us mid-scale: the entry stays for
            # the owner's reconciler; only our claim is dropped.
            self._release_claim(key)
            self._count("failed")
            raise
        with self._lock:
            self.migrated_requests += moved
            self.completed_ops += 1
        labels = {"pod": self._pod} if self._pod else {}
        if moved:
            self._registry.counter_inc(
                FLEET_DRAIN_MIGRATED_REQUESTS_TOTAL, MIGRATED_HELP,
                value=float(moved), **labels,
            )
        self._count("scaled")
        log.info(
            "scale %s: replica %s drained, %d in-flight requests "
            "migrated, released", scale_id, engine, moved,
        )
        return "scaled"

    def _release_claim(self, key: tuple[str, str]) -> None:
        if self._assume is not None:
            self._assume.release(key)


# ---------------------------------------------------------------------------
# restart resolution (called by cluster.reconciler)
# ---------------------------------------------------------------------------


def resolve_scale(
    ckpt: AllocationCheckpoint,
    assume: Any,
    key: tuple[str, str],
    data: Mapping[str, Any],
    *,
    deliver_fn: Callable[[str, dict], Any],
    requeue_fn: Callable[[str, dict], Any] | None = None,
) -> str | None:
    """Resolve one journaled scale-down found after a crash (any phase).

    Roll **forward** at or past ``migrate``: the commit point passed —
    the drained snapshot is in the record; re-deliver it through
    ``deliver_fn`` (the fleet binding's survivor restore — idempotent
    by snapshot_id — plus the release the dead executor never reached),
    then commit. Roll **back** before it: ``requeue_fn`` re-opens the
    replica if it still lives, or re-queues the journaled rows on
    survivors (rid-deduped, full re-prefill), then abort. BOTH
    directions end with every in-flight request scheduled to be served
    exactly once — a scale entry, whatever phase it died in, never
    costs a request.

    Returns ``"rollforward"`` / ``"rollback"`` when resolved this pass,
    None when a side effect failed — the entry stays pending
    (protective) for the next pass, exactly like move and handoff."""
    seq = data.get("_seq")
    phase = str(data.get("phase") or "cordon")
    scale_id = str(data.get("scale_id") or key[1])
    if phase in SCALE_ROLL_FORWARD_PHASES:
        try:
            deliver_fn(scale_id, dict(data))
        except Exception as e:  # noqa: BLE001 — survivor not ready:
            # committing would delete the journal's only copy of the
            # drained snapshot; stay pending for the next pass
            log.warning(
                "scale resolve: re-delivery of %s failed (%s); left "
                "pending", scale_id, e,
            )
            return None
        if _journal_resolve(ckpt, "commit", key, seq):
            if assume is not None:
                assume.release_if_unclaimed(key)
            log.info(
                "scale resolve: %s rolled forward (died in %s)",
                scale_id, phase,
            )
            return "rollforward"
        return None
    # before the commit point: un-cordon the replica if it still lives,
    # or re-queue the journaled rows on survivors (the degradation
    # ladder's floor — a full re-prefill, tokens bit-identical)
    try:
        if requeue_fn is not None:
            requeue_fn(scale_id, dict(data))
    except Exception as e:  # noqa: BLE001 — stay pending
        log.warning(
            "scale resolve: rollback of %s failed (%s); left pending",
            scale_id, e,
        )
        return None
    if _journal_resolve(ckpt, "abort", key, seq):
        if assume is not None:
            assume.release_if_unclaimed(key)
        log.info(
            "scale resolve: %s rolled back (died in %s)",
            scale_id, phase,
        )
        return "rollback"
    return None
