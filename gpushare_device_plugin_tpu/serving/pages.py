"""Paged KV-cache page allocator + slice-aware paged-pool sizing.

The slot pool (PR 5) reserves a full ``max_len`` KV row per admitted
request, so the ``aliyun.com/tpu-mem`` slice strands most of its HBM on
short requests — the exact waste the plugin's fractional-HBM model
exists to eliminate. This module is the host-side half of the paged
replacement (the ParvaGPU direction, PAPERS.md 2409.14447, applied
inside one slice):

- :class:`PageAllocator`: fixed-size KV **pages** carved from the slice
  budget, handed out O(1) from a free-list stack and returned O(1) on
  release. Pages are **reference counted** so the radix prefix cache
  (``serving/radix.py``) can share one physical page between any number
  of requests whose prompts agree on its tokens; a page returns to the
  free list only when the last reference drops.
- :class:`PagedPlan` / :func:`paged_plan_for_slice`: the sizing math
  that converts a byte slice into (dispatch slots, page count) with the
  page-table + free-list overhead **counted against the budget**, so a
  fully-admitted paged pool can never exceed the injected
  ``aliyun.com/tpu-mem`` bytes (exact-budget accounting pinned in
  ``tests/test_pages_radix.py``).

Device-side, the physical cache is ``[L, pages, page_size, Hkv, Dh]``
with page id :data:`SCRATCH` (0) reserved as a write sink for idle
rows — never allocated, never read (``workloads/generate.py`` paged
primitives). The allocator hands out ids ``1..total_pages``.

Thread-safety: the engine's host loop is single-threaded, but the
``/metrics`` endpoint scrapes occupancy from another thread, so counters
sit behind a ranked lock (``serving.pages``, ``utils/lockrank.py``).
"""

from __future__ import annotations

import dataclasses

from ..utils.lockrank import make_lock
from ..utils.metric_catalog import (
    ENGINE_KV_PAGES_FREE,
    ENGINE_KV_PAGES_TOTAL,
    ENGINE_KV_PAGES_USED,
)
from ..utils.metrics import REGISTRY, MetricsRegistry

# Physical page id 0: the scratch page. Idle slot rows' page tables point
# every entry here, so a pool-wide decode step's (masked, never-read)
# writes land somewhere harmless without a dynamic dispatch shape.
SCRATCH = 0

# Host bookkeeping bytes charged per page against the slice budget: a
# free-list slot plus a refcount entry. Deliberately conservative — the
# point is that the accounting test can bound the WHOLE paged pool, not
# that these live in HBM.
FREELIST_BYTES_PER_PAGE = 8


class PageAllocator:
    """O(1) free-list allocator over ``total_pages`` KV pages with
    per-page reference counts.

    ``alloc`` is all-or-nothing (a request's chunk either gets every
    page its write needs or none — partial grants would corrupt the
    page-table invariant that allocated entries are a prefix of the
    row). ``share`` adds a reference (radix prefix sharing);
    ``release`` drops one and recycles the page at zero.
    """

    def __init__(self, total_pages: int) -> None:
        if total_pages < 1:
            raise ValueError(f"total_pages must be >= 1, got {total_pages}")
        self._lock = make_lock("serving.pages")
        self.total = total_pages
        # Stack of free ids (1..total; SCRATCH is never handed out):
        # pop/append from the end — O(1) alloc and free.
        self._free: list[int] = list(range(total_pages, 0, -1))
        self._refs: dict[int, int] = {}
        self.alloc_count = 0
        self.free_count_total = 0
        self.high_water = 0

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self.total - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` fresh pages (refcount 1 each), or None when the free
        list cannot cover all of them (all-or-nothing)."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._lock:
            if n > len(self._free):
                return None
            got = [self._free.pop() for _ in range(n)]
            for p in got:
                self._refs[p] = 1
            self.alloc_count += n
            self.high_water = max(self.high_water, self.total - len(self._free))
            return got

    def share(self, pages: list[int] | tuple[int, ...]) -> None:
        """Add one reference to each page (a prefix-cache hit, or the
        radix tree adopting a retiring request's prompt pages)."""
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise ValueError(f"share of unallocated page {p}")
                self._refs[p] += 1

    def release(self, pages: list[int] | tuple[int, ...]) -> None:
        """Drop one reference from each page; a page whose count hits
        zero returns to the free list (O(1) per page)."""
        with self._lock:
            for p in pages:
                refs = self._refs.get(p)
                if refs is None:
                    raise ValueError(f"release of unallocated page {p}")
                if refs == 1:
                    del self._refs[p]
                    self._free.append(p)
                    self.free_count_total += 1
                else:
                    self._refs[p] = refs - 1

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def freeable(self, groups: list[list[int]]) -> int:
        """How many pages would return to the free list if every listed
        reference were released: each inner list is one holder's pages
        (a preemption victim's row, the radix tree's cached set); a page
        frees only when the groups cover ALL its references. The paged
        engine gates destructive escalation on this, so it never evicts
        a cache or preempts a victim unless the grant will succeed."""
        counts: dict[int, int] = {}
        for group in groups:
            for p in group:
                counts[p] = counts.get(p, 0) + 1
        with self._lock:
            return sum(
                1 for p, c in counts.items() if self._refs.get(p, 0) <= c
            )

    def reset_stats(self) -> None:
        """Zero the cumulative counters (engine warmup flush) — the free
        list and live refcounts are untouched."""
        with self._lock:
            self.alloc_count = 0
            self.free_count_total = 0
            self.high_water = self.total - len(self._free)

    def publish(
        self, registry: MetricsRegistry = REGISTRY, pod: str = ""
    ) -> None:
        """Export occupancy gauges to the ``/metrics`` registry (reads
        under the pages lock, writes to the registry outside it — the
        lock ranking allows the nesting, but there is no reason to hold
        two locks)."""
        with self._lock:
            free = len(self._free)
        labels = {"pod": pod} if pod else {}
        registry.gauge_set(
            ENGINE_KV_PAGES_TOTAL, self.total,
            "KV page-pool capacity (pages)", **labels,
        )
        registry.gauge_set(
            ENGINE_KV_PAGES_FREE, free,
            "KV pages on the free list", **labels,
        )
        registry.gauge_set(
            ENGINE_KV_PAGES_USED, self.total - free,
            "KV pages referenced by live requests or the prefix cache",
            **labels,
        )


# ---------------------------------------------------------------------------
# slice-aware paged-pool sizing
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PagedPlan:
    """A paged pool sized to a byte budget: ``slots`` dispatch rows over
    ``total_pages`` KV pages of ``page_size`` positions each. The byte
    fields are the exact accounting the budget test pins: weights +
    ``kv_bytes`` (pages incl. the scratch page) + ``table_bytes`` (int32
    page tables + per-row len) + ``freelist_bytes`` never exceed the
    slice at the chosen headroom.

    A speculative-decoding engine carries a second, draft-model KV pool
    indexed by the SAME page ids (``serving/engine.py``): every page the
    allocator hands out then costs ``page_bytes + draft_page_bytes``,
    and ``draft_bytes`` charges the whole draft pool (incl. its scratch
    page) against the same slice budget. Both stay 0 for plans sized
    without a draft.

    A multi-LoRA engine additionally carries the paged **adapter slab**
    (``[total_pages + 1, page_size * d_model]`` f32, same page-id
    space — any page can hold KV positions or adapter floats, the
    allocator does not care which): every page then also costs
    ``adapter_page_bytes``, and ``adapter_bytes`` charges the whole slab
    (incl. its permanently-zero scratch row, the null adapter) against
    the same slice. Zero for plans sized without LoRA."""

    slots: int
    total_pages: int
    page_size: int
    page_bytes: int
    kv_bytes: int
    table_bytes: int
    freelist_bytes: int
    draft_page_bytes: int = 0
    draft_bytes: int = 0
    adapter_page_bytes: int = 0
    adapter_bytes: int = 0

    @property
    def max_pages_per_row(self) -> int:
        # set by the planner: table_bytes = slots * (max_pages*4 + 4)
        if self.slots == 0:
            return 0
        return (self.table_bytes // self.slots - 4) // 4

    @property
    def pool_bytes(self) -> int:
        """Everything the paged pool itself pins against the slice."""
        return (
            self.kv_bytes + self.table_bytes + self.freelist_bytes
            + self.draft_bytes + self.adapter_bytes
        )


def pages_for(length: int, page_size: int) -> int:
    """Pages covering ``length`` positions (ceil)."""
    return -(-length // page_size)


def row_span_for(max_len: int, prefill_chunk: int) -> int:
    """Logical positions one request's page table spans: ``max_len``
    rounded UP to a prefill-chunk multiple. The chunk pad tail must map
    to SCRATCH entries rather than clamp into real pages, so the engine
    allocates tables this wide and the sizing math must charge exactly
    the same width — both call here."""
    return -(-max_len // prefill_chunk) * prefill_chunk


def paged_plan_for_slice(
    slice_bytes: int,
    cfg,
    max_len: int,
    *,
    page_size: int,
    weight_bytes: int,
    prefill_chunk: int = 1,
    kv_dtype: str | None = None,
    headroom: float = 0.90,
    slots: int | None = None,
    n_chips: int = 1,
    draft_cfg=None,
    draft_weight_bytes: int = 0,
    lora: bool = False,
) -> PagedPlan:
    """Size a paged pool for a ``slice_bytes`` HBM slice.

    Weights come off the top and ``headroom`` covers activations + XLA
    workspace exactly as in :func:`~.engine.slots_for_slice`; the rest
    buys KV **pages** (plus one scratch page) with the page-table and
    free-list overhead charged against the same budget. ``slots`` (the
    dispatch width — max concurrent requests) defaults to 4x what the
    contiguous slot math would grant, capped at the page count: more
    rows than pages is useless because every admitted request pins at
    least one page. ``n_chips > 1`` sizes over a tensor-parallel gang's
    PER-CHIP share: page bytes and weights divide by the gang size when
    the kv-heads axis shards (mirror of :func:`~.engine.slots_for_gang`).

    ``draft_cfg`` sizes a speculative-decoding draft pool alongside: the
    draft model's weights (``draft_weight_bytes``) come off the top with
    the target's, and every page additionally charges the draft model's
    KV bytes for the same ``page_size`` positions — the two pools share
    one page-id space, so a page either exists in both or neither. tp>1
    shards draft page bytes on the kv-heads axis exactly like the main
    pool (only when ``draft_cfg.kv_heads`` divides evenly).

    ``lora=True`` sizes the multi-LoRA adapter slab alongside: every
    page additionally charges ``page_size * d_model`` f32 slab floats
    (same shared page-id space as the draft pool — a page either exists
    in every device buffer or none). tp>1 shards slab bytes on the
    FEATURE axis (adapter fan-in/out dims all derive from d_model), so
    they divide by the gang only when ``cfg.d_model`` does.

    ``total_pages == 0`` means the slice cannot hold even one page —
    callers must reject, not round up.
    """
    # Late import: engine imports this module for PageAllocator; the
    # per-slot/per-page byte math lives in engine (kv_slot_bytes).
    from .engine import kv_slot_bytes

    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if max_len < page_size:
        raise ValueError(
            f"max_len {max_len} smaller than page_size {page_size}"
        )
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    if n_chips < 1:
        raise ValueError(f"n_chips must be >= 1, got {n_chips}")
    if prefill_chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
    page_b = kv_slot_bytes(cfg, page_size, kv_dtype)
    row_b = kv_slot_bytes(cfg, max_len, kv_dtype)
    if n_chips > 1 and cfg.kv_heads % n_chips == 0:
        page_b = -(-page_b // n_chips)
        row_b = -(-row_b // n_chips)
        weight_bytes = -(-weight_bytes // n_chips)
    dpage_b = 0
    if draft_cfg is not None:
        if draft_weight_bytes < 0:
            raise ValueError(
                f"draft_weight_bytes must be >= 0, got {draft_weight_bytes}"
            )
        dpage_b = kv_slot_bytes(draft_cfg, page_size, kv_dtype)
        if n_chips > 1 and draft_cfg.kv_heads % n_chips == 0:
            dpage_b = -(-dpage_b // n_chips)
            draft_weight_bytes = -(-draft_weight_bytes // n_chips)
        weight_bytes += draft_weight_bytes
    apage_b = 0
    if lora:
        apage_b = page_size * cfg.d_model * 4  # f32 slab floats per page
        if n_chips > 1 and cfg.d_model % n_chips == 0:
            apage_b = -(-apage_b // n_chips)
    # Per-row page-table entries: row_span_for is the exact width
    # PagedSlotEngine allocates, so table_bytes is exact.
    row_span = row_span_for(max_len, prefill_chunk)
    max_pages = pages_for(row_span, page_size)

    def zero() -> PagedPlan:
        return PagedPlan(0, 0, page_size, page_b, 0, 0, 0, dpage_b, 0, apage_b, 0)

    usable = int(slice_bytes * headroom) - weight_bytes
    if usable <= 0:
        return zero()

    def pages_at(n_slots: int) -> int:
        table = n_slots * (max_pages * 4 + 4)
        # scratch page off the top (target + draft + adapter slab row),
        # then each page costs its bytes in EVERY pool plus its
        # free-list/refcount bookkeeping share
        left = usable - table - (page_b + dpage_b + apage_b)
        if left <= 0:
            return 0
        return left // (page_b + dpage_b + apage_b + FREELIST_BYTES_PER_PAGE)

    if slots is None:
        contiguous = max(usable // row_b, 1)
        slots = max(1, min(pages_at(1), 4 * contiguous))
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    pages = pages_at(slots)
    # More rows than pages is dead weight; shrinking slots only grows
    # pages, so one clamp+recompute converges.
    if pages and slots > pages:
        slots = pages
        pages = pages_at(slots)
    if pages < 1:
        return zero()
    return PagedPlan(
        slots=int(slots),
        total_pages=int(pages),
        page_size=page_size,
        page_bytes=page_b,
        kv_bytes=(int(pages) + 1) * page_b,
        table_bytes=int(slots) * (max_pages * 4 + 4),
        freelist_bytes=int(pages) * FREELIST_BYTES_PER_PAGE,
        draft_page_bytes=dpage_b,
        draft_bytes=(int(pages) + 1) * dpage_b if dpage_b else 0,
        adapter_page_bytes=apage_b,
        adapter_bytes=(int(pages) + 1) * apage_b if apage_b else 0,
    )
