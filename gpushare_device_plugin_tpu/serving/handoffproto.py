"""Journaled prefill→decode KV-handoff protocol, as a jax-free core.

Disaggregated serving splits one slice's engine into a **prefill tier**
(fills paged KV, produces the first token) and a **decode tier** (streams
the rest). The KV pages a prefill engine produced must MOVE to the
decode engine — across processes, across crashes — without ever losing a
request, serving one twice, or leaking a destination page. This module
is the protocol half of that story, deliberately free of jax and engine
state so ``tools/tpumc`` can enumerate every interleaving of the REAL
code (like ``drainproto.py`` before it) and the chaos suite can SIGKILL
it at every journal step (``make chaos-handoff``).

The state machine generalizes the PR 10 move protocol
(``allocator/defrag.py``): one handoff = WAL record kind ``"handoff"``
journaled through the phases

    export -> transfer -> import -> commit

each durable *before* its side effect:

- **export**: the full request row (prompt, first token, SLO targets)
  is durable, then the wire payload (page bytes + CRC32 checksums) is
  materialized. From here a crash can re-serve the request from the
  journal alone — the decode tier re-prefills it locally.
- **transfer**: record durable, then the peer stages destination pages
  through the decode tier's refcounted :class:`~.pages.PageAllocator`
  (all-or-nothing) and receives page bytes one page at a time, each
  checksum-verified on arrival.
- **import**: the **commit point**. Record durable, then the decode tier
  adopts the staged pages into a live row. At or past this phase a
  crash rolls FORWARD (re-deliver, idempotent by handoff id — the
  ``snapshot_id`` dedup discipline of the move protocol); before it, a
  crash rolls BACK (release staged pages, degrade to local re-prefill).
- **commit**: record durable, then the source drops its export buffer;
  the WAL entry resolves.

Every delivery — KV import, duplicate, or re-prefill fallback — funnels
through ONE idempotent sink (:class:`HandoffSink`) gated by
:meth:`HandoffImportLedger.first_delivery`, so at-least-once re-delivery
across any crash window can never serve a request twice, and a failed or
timed-out transfer degrades to re-prefill instead of losing the request
(greedy decoding is deterministic, so the tokens are bit-identical
either way; ``tests/test_handoff.py`` pins both).

Page transfer rides :class:`HandoffPeerClient` — ``utils/retry.py``
backoff with a per-transfer deadline over a ``utils/circuit.py`` breaker
— so a flapping decode tier costs bounded wall clock, never a wedged
prefill engine.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from collections import deque
from typing import Any, Callable, Mapping

from ..allocator.checkpoint import AllocationCheckpoint, StaleDaemonError
from ..utils.circuit import CircuitBreaker, CircuitOpenError
from ..utils.faults import FAULTS
from ..utils.lockrank import make_lock
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.retry import retry
from ..utils.metric_catalog import (
    HANDOFF_BYTES,
    HANDOFF_FALLBACK_REPREFILL_TOTAL,
    HANDOFF_PAGES_IN_FLIGHT,
    HANDOFF_TRANSFER_SECONDS,
    HANDOFF_TRANSFERS_TOTAL,
)

log = get_logger("serving.handoff")

# The journaled handoff state machine, in order. Each phase's WAL record
# is durable BEFORE its side effect; "import" is the roll-forward
# boundary (the analogue of the move protocol's "switch").
HANDOFF_PHASES = ("export", "transfer", "import", "commit")
HANDOFF_KIND = "handoff"
ROLL_FORWARD_PHASES = ("import", "commit")

# Synthetic namespace for handoff journal/ledger keys, like the defrag
# mover's DEFRAG_NS: the entry is keyed by handoff id, never mistaken
# for (or hidden by) a real pod's own accounting.
HANDOFF_NS = "tpushare-handoff"

TRANSFERS_HELP = (
    "Cross-engine KV handoffs by outcome "
    "(delivered/duplicate/fallback/failed)"
)
TRANSFER_SECONDS_HELP = "Wall time of one completed KV handoff, all phases"
BYTES_HELP = "KV page bytes shipped per completed handoff transfer"
FALLBACK_HELP = (
    "Handoffs degraded to local re-prefill on the decode tier, by reason"
)
PAGES_IN_FLIGHT_HELP = (
    "Destination pages reserved for handoffs still staging (not yet "
    "adopted or released)"
)


class ChecksumError(ValueError):
    """A transferred page's CRC32 did not match its payload."""


class HandoffError(RuntimeError):
    """A handoff could not proceed (transfer dead, staging refused)."""


def handoff_key(handoff_id: str) -> tuple[str, str]:
    """The journal/ledger key for one handoff (synthetic namespace)."""
    return (HANDOFF_NS, handoff_id)


def page_crc(blob: bytes) -> int:
    """CRC32 over one serialized page's wire bytes."""
    return zlib.crc32(blob) & 0xFFFFFFFF


def _journal_handoff(
    ckpt: AllocationCheckpoint | None, key: tuple[str, str], data: dict
) -> int | None:
    """Journal one handoff phase durable (a fresh ``begin`` for the
    handoff key — the loader keeps the newest record per key, so the
    entry always names the furthest phase reached, exactly like the move
    protocol's ``_journal_phase``). ``StaleDaemonError`` propagates: a
    fenced daemon must not advance a handoff the newer incarnation owns.
    ``None`` = journal degraded (sick disk): the handoff continues
    unjournaled, like admissions do. (tpulint's wal-protocol rule knows
    this helper as a ``begin`` form — every call site must be dominated
    by :func:`_journal_resolve` on its handled paths.)"""
    if ckpt is None:
        return None
    return ckpt.begin(key, data)


def _journal_resolve(
    ckpt: AllocationCheckpoint | None,
    op: str,
    key: tuple[str, str],
    seq: int | None,
) -> bool:
    """Resolve the handoff's journal entry (``op`` = ``"commit"`` the
    pages were delivered, ``"abort"`` the handoff degraded/rolled back);
    the thin delegation form the wal-protocol rule recognizes. False =
    degraded/unjournaled or a newer begin owns the key."""
    if ckpt is None:
        return False
    if op == "commit":
        return ckpt.commit(key, seq=seq)
    return ckpt.abort(key, seq=seq)


# ---------------------------------------------------------------------------
# decode-tier import ledger
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Staged:
    pages: list[int]
    blobs: list[bytes | None]
    meta: dict

    def sealed(self) -> bool:
        return all(b is not None for b in self.blobs)


class HandoffImportLedger:
    """The decode tier's staging table: destination pages reserved per
    in-flight handoff, page bytes accumulated as they arrive, and the
    delivered-id window that makes delivery idempotent.

    Thread-safe under rank ``serving.handoff`` (below ``serving.pages``,
    so staging may call the page allocator while holding it). Page
    ownership: :meth:`stage` reserves pages refcount-1 through the
    caller's allocator; :meth:`adopt` transfers them to the engine row
    (the row's release recycles them); :meth:`abort` releases them here.
    Exactly one of adopt/abort ends every staging — the chaos suite's
    zero-leaked-pages gate counts on it.
    """

    def __init__(self, dedup_window: int = 64) -> None:
        self._lock = make_lock("serving.handoff")
        self._staged: dict[str, _Staged] = {}
        # handoff ids already delivered (served via KV import OR
        # re-prefill fallback): the at-least-once re-delivery across the
        # import/commit crash window dedups here, like snapshot_id dedup
        # in PagedSlotEngine.restore_snapshot.
        self._delivered: deque[str] = deque(maxlen=dedup_window)

    def stage(
        self,
        handoff_id: str,
        n_pages: int,
        meta: Mapping[str, Any],
        alloc: Callable[[int], list[int] | None],
    ) -> list[int] | None:
        """Reserve ``n_pages`` destination pages for a handoff
        (all-or-nothing through ``alloc``). Idempotent: a re-stage of a
        live staging returns its existing pages. None = nothing staged
        (pool cannot cover it, or the handoff was already delivered) —
        the mover degrades to re-prefill."""
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        with self._lock:
            if handoff_id in self._delivered:
                return None
            st = self._staged.get(handoff_id)
            if st is not None:
                return list(st.pages)
            got = alloc(n_pages)
            if got is None:
                return None
            self._staged[handoff_id] = _Staged(
                pages=got, blobs=[None] * n_pages, meta=dict(meta)
            )
            return list(got)

    def put_page(
        self, handoff_id: str, index: int, blob: bytes, crc: int
    ) -> None:
        """Store one transferred page's bytes, checksum-verified on
        arrival (:class:`ChecksumError` — the peer client retries the
        page). ``LookupError`` when nothing is staged under the id."""
        if page_crc(blob) != crc:
            raise ChecksumError(
                f"handoff {handoff_id} page {index}: checksum mismatch"
            )
        with self._lock:
            st = self._staged.get(handoff_id)
            if st is None:
                raise LookupError(f"handoff {handoff_id} is not staged")
            if not 0 <= index < len(st.blobs):
                raise IndexError(
                    f"handoff {handoff_id} page index {index} out of "
                    f"range (staged {len(st.blobs)})"
                )
            st.blobs[index] = blob

    def adopt(self, handoff_id: str) -> tuple[list[int], list[bytes], dict] | None:
        """Pop a SEALED staging (every page present) for engine import —
        page ownership transfers to the caller. None when absent or
        still partial (the delivery falls back to re-prefill)."""
        with self._lock:
            st = self._staged.get(handoff_id)
            if st is None or not st.sealed():
                return None
            del self._staged[handoff_id]
            return (st.pages, [b for b in st.blobs if b is not None], st.meta)

    def abort(
        self, handoff_id: str, release: Callable[[list[int]], None]
    ) -> bool:
        """Drop a staging and release its reserved pages (rollback, or
        leftover partial staging after a fallback delivery)."""
        with self._lock:
            st = self._staged.pop(handoff_id, None)
            if st is None:
                return False
            release(st.pages)
            return True

    def first_delivery(self, handoff_id: str) -> bool:
        """The idempotent-delivery gate: True exactly once per handoff
        id. Every serve path (KV import AND re-prefill fallback) passes
        here first, so duplicate re-deliveries are no-ops."""
        with self._lock:
            if handoff_id in self._delivered:
                return False
            self._delivered.append(handoff_id)
            return True

    def delivered(self, handoff_id: str) -> bool:
        with self._lock:
            return handoff_id in self._delivered

    @property
    def pages_in_flight(self) -> int:
        with self._lock:
            return sum(len(st.pages) for st in self._staged.values())

    def publish(
        self, registry: MetricsRegistry = REGISTRY, pod: str = ""
    ) -> None:
        labels = {"pod": pod} if pod else {}
        registry.gauge_set(
            HANDOFF_PAGES_IN_FLIGHT, float(self.pages_in_flight),
            PAGES_IN_FLIGHT_HELP, **labels,
        )

    def doc(self) -> dict[str, Any]:
        """Staging state for debugging and the model checker's checks."""
        with self._lock:
            return {
                "staged": {
                    hid: {
                        "pages": list(st.pages),
                        "received": sum(b is not None for b in st.blobs),
                        "total": len(st.blobs),
                    }
                    for hid, st in self._staged.items()
                },
                "delivered": list(self._delivered),
            }


# ---------------------------------------------------------------------------
# decode-tier delivery sink
# ---------------------------------------------------------------------------


class HandoffSink:
    """The decode tier's delivery endpoint: staging plus the ONE
    idempotent serve path every handoff ends in.

    ``import_cb(pages, blobs, meta, record)`` adopts sealed staged pages
    into the decode engine (ownership transfers — the engine releases
    them when the request retires); a raise falls back to re-prefill
    with the pages released here. ``reprefill_cb(record)`` queues the
    journaled request row for local re-prefill — it must not raise (it
    only stages host state; the request would otherwise be marked
    delivered but never served).
    """

    def __init__(
        self,
        ledger: HandoffImportLedger,
        alloc: Callable[[int], list[int] | None],
        release: Callable[[list[int]], None],
        import_cb: Callable[[list[int], list[bytes], dict, dict], None],
        reprefill_cb: Callable[[dict], None],
        *,
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        self.ledger = ledger
        self._alloc = alloc
        self._release = release
        self._import = import_cb
        self._reprefill = reprefill_cb
        self._registry = registry
        self._pod = pod

    # --- transfer side ----------------------------------------------------

    def stage(
        self, handoff_id: str, n_pages: int, meta: Mapping[str, Any]
    ) -> bool:
        return (
            self.ledger.stage(handoff_id, n_pages, meta, self._alloc)
            is not None
        )

    def put_page(
        self, handoff_id: str, index: int, blob: bytes, crc: int
    ) -> None:
        self.ledger.put_page(handoff_id, index, blob, crc)

    def abort(self, handoff_id: str) -> bool:
        return self.ledger.abort(handoff_id, self._release)

    # --- the idempotent serve path ----------------------------------------

    def deliver(self, handoff_id: str, record: Mapping[str, Any]) -> str:
        """Serve one handoff exactly once: ``"imported"`` (staged KV
        adopted), ``"reprefill"`` (no usable staging — the journaled
        request re-prefills locally), or ``"duplicate"`` (already
        served; leftover staging is released). Idempotent by handoff id
        — safe under the at-least-once re-delivery every crash window
        implies."""
        if not self.ledger.first_delivery(handoff_id):
            # duplicate re-delivery: the request was already served;
            # drop any staging a racing transfer left behind
            self.ledger.abort(handoff_id, self._release)
            log.warning(
                "handoff %s already delivered; duplicate ignored",
                handoff_id,
            )
            return "duplicate"
        got = self.ledger.adopt(handoff_id)
        if got is None:
            # nothing staged, or a partial transfer: release the partial
            # reservation and serve by local re-prefill — the request is
            # never lost, it just costs a prefill (tokens bit-identical
            # by greedy determinism)
            self.ledger.abort(handoff_id, self._release)
            self._reprefill(dict(record))
            self._count_fallback("no_staged_kv")
            return "reprefill"
        pages, blobs, meta = got
        try:
            self._import(pages, blobs, meta, dict(record))
        except Exception as e:  # noqa: BLE001 — geometry mismatch etc.:
            # the pages cannot serve here; degrade rather than lose
            self._release(pages)
            self._reprefill(dict(record))
            self._count_fallback("import_failed")
            log.warning(
                "handoff %s import failed (%s); degraded to re-prefill",
                handoff_id, e,
            )
            return "reprefill"
        return "imported"

    def _count_fallback(self, reason: str) -> None:
        labels = {"pod": self._pod} if self._pod else {}
        self._registry.counter_inc(
            HANDOFF_FALLBACK_REPREFILL_TOTAL, FALLBACK_HELP,
            reason=reason, **labels,
        )


# ---------------------------------------------------------------------------
# retrying peer client
# ---------------------------------------------------------------------------


class HandoffPeerClient:
    """Transfer-side client over a duck-typed transport (``stage`` /
    ``put_page`` / ``deliver`` / ``abort``): every verb retries with
    exponential backoff under a per-call deadline, behind a shared
    circuit breaker so a dead decode tier fails fast instead of
    serializing full retry ladders per page.

    The lock (rank ``handoff.peer``) guards the transfer counters only —
    never held across a transport call or the breaker."""

    def __init__(
        self,
        transport: Any,
        *,
        attempts: int = 3,
        delay_s: float = 0.02,
        backoff: float = 2.0,
        deadline_s: float = 2.0,
        breaker: CircuitBreaker | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._t = transport
        self._attempts = attempts
        self._delay = delay_s
        self._backoff = backoff
        self._deadline = deadline_s
        self._breaker = breaker or CircuitBreaker(
            "handoff-peer", failure_threshold=5, reset_timeout_s=1.0,
            clock=clock,
        )
        self._sleep = sleep
        self._clock = clock
        self._lock = make_lock("handoff.peer")
        self.calls = 0
        self.retries = 0
        self.sent_pages = 0
        self.sent_bytes = 0

    def _call(self, fn: Callable[[], Any]) -> Any:
        tried = 0

        def once() -> Any:
            nonlocal tried
            tried += 1
            self._breaker.before()
            try:
                out = fn()
            except Exception:
                self._breaker.record_failure()
                raise
            self._breaker.record_success()
            return out

        try:
            out = retry(
                once,
                attempts=self._attempts,
                delay_s=self._delay,
                backoff=self._backoff,
                deadline_s=self._deadline,
                # an OPEN breaker is a fail-fast verdict, not a blip
                retryable=lambda e: not isinstance(e, CircuitOpenError),
                sleep=self._sleep,
                clock=self._clock,
            )
        finally:
            with self._lock:
                self.calls += 1
                self.retries += max(tried - 1, 0)
        return out

    def stage(
        self, handoff_id: str, n_pages: int, meta: Mapping[str, Any]
    ) -> bool:
        return bool(self._call(lambda: self._t.stage(handoff_id, n_pages, meta)))

    def put_page(
        self, handoff_id: str, index: int, blob: bytes, crc: int
    ) -> None:
        self._call(lambda: self._t.put_page(handoff_id, index, blob, crc))
        with self._lock:
            self.sent_pages += 1
            self.sent_bytes += len(blob)

    def deliver(self, handoff_id: str, record: Mapping[str, Any]) -> str:
        return str(self._call(lambda: self._t.deliver(handoff_id, record)))

    def abort(self, handoff_id: str) -> bool:
        return bool(self._call(lambda: self._t.abort(handoff_id)))

    def doc(self) -> dict[str, Any]:
        with self._lock:
            return {
                "calls": self.calls,
                "retries": self.retries,
                "sent_pages": self.sent_pages,
                "sent_bytes": self.sent_bytes,
            }


# ---------------------------------------------------------------------------
# the journaled mover
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HandoffPlan:
    """One prefill→decode handoff: the JSON-safe request row (everything
    the decode tier needs to serve it WITHOUT the KV — the re-prefill
    guarantee), engine geometry ``meta``, and the serialized page
    payloads. The row and meta travel inside every journal record; the
    page bytes never do (like the move protocol journaling the drained
    snapshot, not the cache)."""

    handoff_id: str
    request: dict
    meta: dict
    pages: tuple[bytes, ...]


class HandoffMover:
    """Executes one :class:`HandoffPlan` through the journaled protocol.

    ``peer`` is the transfer path to the decode tier (normally a
    :class:`HandoffPeerClient`); ``fallback_fn(handoff_id, record)``
    is the control-plane path that queues the journaled request for
    local re-prefill when the transfer degrades — it must reach the
    decode tier's :class:`HandoffSink` (dedup included), not the page
    transport that just failed. Exceptions out of :meth:`execute` leave
    the journal entry pending for the reconciler — deliberately: that IS
    the crash-safety story, same as the defrag mover."""

    def __init__(
        self,
        checkpoint: AllocationCheckpoint | None,
        assume: Any,
        peer: Any,
        *,
        fallback_fn: Callable[[str, dict], str],
        node: str = "",
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        self._ckpt = checkpoint
        self._assume = assume
        self._peer = peer
        self._fallback = fallback_fn
        self._node = node
        self._registry = registry
        self._pod = pod

    def _count(self, outcome: str) -> None:
        labels = {"pod": self._pod} if self._pod else {}
        self._registry.counter_inc(
            HANDOFF_TRANSFERS_TOTAL, TRANSFERS_HELP, outcome=outcome,
            **labels,
        )

    def execute(self, plan: HandoffPlan) -> str:
        """Run one handoff end to end: ``"delivered"`` (KV adopted on
        the decode tier), ``"duplicate"`` (the decode tier had already
        served it), or ``"fallback"`` (transfer degraded — the request
        re-prefills on the decode tier). Raises when even the fallback
        path is unreachable: the entry stays pending and the reconciler
        re-delivers — the request is delayed, never lost."""
        key = handoff_key(plan.handoff_id)
        if self._assume is not None and not self._assume.claim(key):
            # a concurrent mover owns this handoff (the reconciler's
            # claim gate protects it the same way)
            log.v(4, "handoff %s already in flight; skipped", plan.handoff_id)
            return "skipped"
        t0 = time.perf_counter()
        base = {
            "kind": HANDOFF_KIND,
            "handoff_id": plan.handoff_id,
            "request": plan.request,
            "meta": plan.meta,
            "n_pages": len(plan.pages),
            "node": self._node,
        }
        try:
            # export: the request row is durable before the wire payload
            # exists — any crash from here on can re-serve the request
            # from the journal alone.
            seq = _journal_handoff(self._ckpt, key, {**base, "phase": "export"})
            FAULTS.fire("handoff.export")
            blobs = list(plan.pages)
            crcs = [page_crc(b) for b in blobs]
            nbytes = sum(len(b) for b in blobs)
            # transfer: record durable, then pages ship one at a time —
            # destination pages reserved (all-or-nothing) first.
            seq = _journal_handoff(self._ckpt, key, {**base, "phase": "transfer"})
            FAULTS.fire("handoff.transfer")
            staged = False
            try:
                staged = bool(blobs) and self._peer.stage(
                    plan.handoff_id, len(blobs), plan.meta
                )
                if staged:
                    for i, (blob, crc) in enumerate(zip(blobs, crcs)):
                        self._peer.put_page(plan.handoff_id, i, blob, crc)
            except Exception as e:  # noqa: BLE001 — transfer dead after
                # retries/deadline/breaker: degrade. The staged partial
                # reservation is released best-effort here and
                # authoritatively by the fallback delivery's own abort.
                log.warning(
                    "handoff %s transfer failed (%s); degrading to "
                    "re-prefill", plan.handoff_id, e,
                )
                try:
                    self._peer.abort(plan.handoff_id)
                except Exception as abort_err:  # noqa: BLE001
                    # same dead transport; the fallback delivery's own
                    # abort is the authoritative release
                    log.v(
                        4, "handoff %s staging abort also failed: %s",
                        plan.handoff_id, abort_err,
                    )
                self._fallback(plan.handoff_id, dict(base))
                _journal_resolve(self._ckpt, "abort", key, seq)
                self._release_claim(key)
                self._count("fallback")
                return "fallback"
            if not staged:
                # the decode pool cannot reserve the pages (or the
                # handoff was already served): no transfer — the
                # fallback delivery settles which, idempotently.
                self._fallback(plan.handoff_id, dict(base))
                _journal_resolve(self._ckpt, "abort", key, seq)
                self._release_claim(key)
                self._count("fallback")
                return "fallback"
            # import: the commit point — at or past this record a crash
            # rolls forward (re-deliver by handoff id).
            seq = _journal_handoff(self._ckpt, key, {**base, "phase": "import"})
            FAULTS.fire("handoff.import")
            outcome = self._peer.deliver(plan.handoff_id, base)
            # commit: source-side cleanup (the export buffer dies with
            # this frame), then the entry resolves.
            seq = _journal_handoff(self._ckpt, key, {**base, "phase": "commit"})
            FAULTS.fire("handoff.commit")
            del blobs
            _journal_resolve(self._ckpt, "commit", key, seq)
            self._release_claim(key)
        except StaleDaemonError:
            # a newer daemon fenced us mid-handoff: the entry stays for
            # the owner's reconciler; only our claim is dropped.
            self._release_claim(key)
            self._count("failed")
            raise
        wall = time.perf_counter() - t0
        labels = {"pod": self._pod} if self._pod else {}
        if outcome == "duplicate":
            self._count("duplicate")
            return "duplicate"
        self._count("delivered")
        self._registry.observe(
            HANDOFF_TRANSFER_SECONDS, wall, TRANSFER_SECONDS_HELP, **labels
        )
        self._registry.observe(
            HANDOFF_BYTES, float(nbytes), BYTES_HELP,
            buckets=(4096.0, 65536.0, 1048576.0, 16777216.0, 268435456.0),
            **labels,
        )
        return "delivered"

    def _release_claim(self, key: tuple[str, str]) -> None:
        if self._assume is not None:
            self._assume.release(key)


# ---------------------------------------------------------------------------
# restart resolution (called by cluster.reconciler)
# ---------------------------------------------------------------------------


def resolve_handoff(
    ckpt: AllocationCheckpoint,
    assume: Any,
    key: tuple[str, str],
    data: Mapping[str, Any],
    *,
    deliver_fn: Callable[[str, dict], str],
    abort_fn: Callable[[str], Any] | None = None,
) -> str | None:
    """Resolve one journaled handoff found after a crash (any phase).

    Roll **forward** at or past ``import``: the commit point passed —
    re-deliver through ``deliver_fn`` (the decode tier's
    :meth:`HandoffSink.deliver`: staged KV adopts if it survived,
    otherwise the journaled request re-prefills; either way idempotent
    by handoff id), then commit. Roll **back** before it: release any
    staged destination pages (``abort_fn``), deliver the journaled
    request for local re-prefill, then abort. BOTH directions end in a
    delivery — a handoff entry, whatever phase it died in, always serves
    its request exactly once.

    Returns ``"rollforward"`` / ``"rollback"`` when resolved this pass,
    None when a delivery side effect failed — the entry stays pending
    (protective) for the next pass, exactly like an unreachable
    apiserver leaves a move pending."""
    seq = data.get("_seq")
    phase = str(data.get("phase") or "export")
    handoff_id = str(data.get("handoff_id") or key[1])
    if phase in ROLL_FORWARD_PHASES:
        try:
            deliver_fn(handoff_id, dict(data))
        except Exception as e:  # noqa: BLE001 — decode tier not ready:
            # committing would delete the journal's only copy of the
            # request row; stay pending for the next pass
            log.warning(
                "handoff resolve: re-delivery of %s failed (%s); left "
                "pending", handoff_id, e,
            )
            return None
        if _journal_resolve(ckpt, "commit", key, seq):
            if assume is not None:
                assume.release_if_unclaimed(key)
            log.info(
                "handoff resolve: %s rolled forward (died in %s)",
                handoff_id, phase,
            )
            return "rollforward"
        return None
    # before the commit point: release staged pages, then serve the
    # journaled request by local re-prefill (degradation ladder's floor)
    try:
        if abort_fn is not None:
            abort_fn(handoff_id)
        deliver_fn(handoff_id, dict(data))
    except Exception as e:  # noqa: BLE001 — stay pending
        log.warning(
            "handoff resolve: rollback delivery of %s failed (%s); left "
            "pending", handoff_id, e,
        )
        return None
    if _journal_resolve(ckpt, "abort", key, seq):
        if assume is not None:
            assume.release_if_unclaimed(key)
        log.info(
            "handoff resolve: %s rolled back to re-prefill (died in %s)",
            handoff_id, phase,
        )
        return "rollback"
    return None
