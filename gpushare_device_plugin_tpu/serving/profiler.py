"""Per-slice decode-step profiler: the raw signal of the interference
observability plane.

Two pods sharing one chip only partition HBM — compute contention is
invisible until it shows up as *slower decode steps* on the
latency-critical tenant. This module measures exactly that, at the only
place it can be measured honestly: around each pool-wide decode dispatch
in the serving engine's host loop.

Design constraints (the same bar the PR 8 tracing layer set):

- **Zero per-token allocation.** One :meth:`StepProfiler.record` call
  per decode *step* (not per token — a step advances every occupied
  slot), writing one float into a preallocated ring under a near-leaf
  lock (``serving.profiler``, rank 91). No list growth, no dict churn,
  no id generation on the hot path.
- **Retire-time style export.** The raw samples stay in the ring;
  :meth:`StepProfiler.flush` batch-converts everything recorded since
  the last flush into ``tpushare_engine_step_seconds`` histogram
  observations (with a trace-id exemplar via a short ``serve.step_flush``
  span) and publishes the rolling p50/p99 gauges — the engine calls it
  once per :meth:`~.engine.SlotEngine.run`, never per step.
- **Rolling quantiles.** p50/p99 over the ring's window (newest
  ``capacity`` steps) — what the interference detector compares against
  each engine's solo baseline window (``cluster/interference.py``).

The profiler's overhead is gated by ``bench_mfu.py --interference-smoke``
(same traced-vs-untraced methodology as ``make bench-trace``): p99 step
time on an uncontended engine inflates <= 5% with profiling on.
"""

from __future__ import annotations

import math

from ..utils.lockrank import make_lock
from ..utils.metrics import MetricsRegistry, REGISTRY
from ..utils.tracing import TRACER

from ..utils.metric_catalog import ENGINE_STEP_SECONDS as STEP_METRIC
from ..utils.metric_catalog import (
    ENGINE_STEP_P50_SECONDS as P50_GAUGE,
    ENGINE_STEP_P99_SECONDS as P99_GAUGE,
)
STEP_HELP = (
    "Wall seconds per pool-wide decode step (one model dispatch advancing "
    "every occupied slot)"
)
# Decode steps span ~100us (real TPU) to ~100ms (CPU smoke); log-spaced so
# both regimes land in resolving buckets.
STEP_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0,
)



def ceil_rank_quantile(vals: list[float], q: float) -> float:
    """Ceil-rank quantile over an unsorted sample list (nan when empty)
    — THE quantile convention this repo's serving stats, profiler, and
    benches all share (one implementation, no drift)."""
    s = sorted(vals)
    if not s:
        return float("nan")
    return s[min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))]


class StepProfiler:
    """Bounded ring of per-decode-step wall times with rolling quantiles.

    Single writer (the engine's host loop), concurrent readers (the
    /metrics publisher, the interference detector). ``capacity`` bounds
    both memory and the rolling window the quantiles answer over.
    """

    def __init__(self, capacity: int = 1024) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._lock = make_lock("serving.profiler")
        self._ring: list[float] = [0.0] * capacity
        # Tokens emitted by each recorded step: 1.0 for a plain decode
        # dispatch, the batch-mean accepted length for a speculative
        # verify round — so interference verdicts and SLO budgets can
        # normalize step time by the work a step actually retired.
        self._tokens: list[float] = [1.0] * capacity
        self._cap = capacity
        self._count = 0  # total steps ever recorded
        self._flushed = 0  # steps already exported to the histogram

    def record(self, seconds: float, tokens: float = 1.0) -> None:
        """One decode step's wall time (and the tokens it emitted per
        slot — >1 when a speculative verify accepted a run). O(1): ring
        writes and a counter bump under the near-leaf lock — no
        allocation."""
        with self._lock:
            self._ring[self._count % self._cap] = seconds
            self._tokens[self._count % self._cap] = tokens
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def window(self) -> list[float]:
        """The rolling window's samples (newest ``capacity`` steps),
        unordered — quantile input for readers that want their own math."""
        with self._lock:
            n = min(self._count, self._cap)
            return self._ring[:n]

    def tokens_per_step(self) -> float:
        """Rolling mean tokens-per-slot-per-step over the window: 1.0
        for a plain engine, the mean accepted length (>= 1) when
        speculative verify rounds dominate; nan with no samples."""
        with self._lock:
            n = min(self._count, self._cap)
            if not n:
                return float("nan")
            return sum(self._tokens[:n]) / n

    def quantile(self, q: float) -> float:
        """Rolling quantile over the window; nan with no samples (same
        ceil-rank convention as ``ServeStats``)."""
        return ceil_rank_quantile(self.window(), q)

    def p50(self) -> float:
        return self.quantile(0.50)

    def p99(self) -> float:
        return self.quantile(0.99)

    def reset(self) -> None:
        """Forget all samples (engine warmup: compile-time steps must not
        pollute the steady-state window)."""
        with self._lock:
            self._count = 0
            self._flushed = 0

    def flush(
        self, registry: MetricsRegistry | None = None, pod: str = ""
    ) -> int:
        """Batch-export everything recorded since the last flush into the
        ``tpushare_engine_step_seconds`` histogram plus the rolling
        p50/p99 gauges; returns the number of samples exported.

        Runs inside a short ``serve.step_flush`` span so the histogram
        buckets carry a trace-id exemplar linking ``/metrics`` to
        ``/traces`` (the per-step ring itself records no trace state —
        zero hot-path cost). Samples that fell off the ring between
        flushes are skipped and counted in the span's ``dropped``
        attribute; the engine flushes once per run, so in practice the
        window covers everything.

        Without a ``pod`` label nothing is exported (returns 0, samples
        consumed): every ``tpushare_engine_*`` series carries the pod
        label, and an unlabeled flush would merge every label-less
        engine in the process into one shared series the interference
        detector cannot attribute. The rolling quantiles stay available
        programmatically either way."""
        reg = registry if registry is not None else REGISTRY
        with self._lock:
            count = self._count
            start = max(self._flushed, count - self._cap)
            dropped = start - self._flushed
            samples = [self._ring[i % self._cap] for i in range(start, count)]
            self._flushed = count
        if not pod:
            return 0
        labels = {"pod": pod}
        if samples:
            with TRACER.span(
                "serve.step_flush",
                attributes={"steps": len(samples), "dropped": dropped},
            ):
                for s in samples:
                    reg.observe(
                        STEP_METRIC, s, STEP_HELP, buckets=STEP_BUCKETS,
                        **labels,
                    )
        p50, p99 = self.p50(), self.p99()
        if p50 == p50:  # not nan
            reg.gauge_set(
                P50_GAUGE, p50,
                "Rolling p50 decode-step wall seconds (profiler window)",
                **labels,
            )
        if p99 == p99:
            reg.gauge_set(
                P99_GAUGE, p99,
                "Rolling p99 decode-step wall seconds (profiler window)",
                **labels,
            )
        return len(samples)
