"""Radix-tree shared-prefix cache over KV pages.

Requests that share a system prompt should prefill it ONCE: the
prefill-once/branch-many cache-snapshot semantics already pinned by
``test_prefix_cache_reuse_branches_continuations`` (caches are immutable
pytrees; a branch never invalidates the snapshot), lifted from a
host-managed snapshot object to the paged slot pool. The tree maps token
sequences to the physical pages holding their KV:

- **Edges are one page wide.** Every node owns exactly one page and the
  ``page_size`` tokens it caches; a path from the root spells a prompt
  prefix in full pages. This is the fixed-stride radix layout (one dict
  hop per page — the block-hash design ParvaGPU-era serving stacks use)
  rather than arbitrary-length compressed edges: page granularity is
  what the allocator shares, so finer edges could never match more.
- **Reference counting, not copying.** :meth:`match` hands back the
  matched pages and takes one allocator reference per page for the
  requesting row; the tree holds its own reference from
  :meth:`insert`. A page is recycled only when the tree evicts it AND
  no live request still reads it — eviction during use is safe by
  construction.
- **LRU leaf eviction.** :meth:`evict` releases least-recently-matched
  leaves first (a parent is only evictable after all its children),
  preserving the prefix property: every cached path stays contiguous
  from the root.

Correctness note (why sharing preserves bit-identity): a page caches
positions ``[i*ps, (i+1)*ps)`` of a prompt, and a position's K/V depend
only on tokens at or before it (causal attention; pad/neighbor lanes
contribute exact zeros — the same visibility invariant the slot pool
relies on). Two prompts that agree on a page's tokens therefore compute
bitwise-identical page contents, so reading one request's page from
another request's row is indistinguishable from having prefilled it —
pinned against solo ``generate()`` in ``tests/test_paged_engine.py``.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Iterator

from ..utils.lockrank import make_lock
from .pages import PageAllocator


def prefix_fingerprints(
    tokens: tuple[int, ...], page_size: int
) -> list[int]:
    """Chained CRC32 fingerprint of each full-page prefix of ``tokens``.

    ``fp[i]`` hashes pages ``0..i`` — each page's CRC is seeded with its
    parent's, so a fingerprint commits to the whole path from the root,
    not just one page's tokens (two different prefixes can never collide
    into sharing a fingerprint chain by agreeing on a single page).
    This is the request-side half of the fleet router's affinity signal:
    an engine exports the same chained values for its cached radix paths
    (:meth:`RadixCache.fingerprints`), and the overlap length is exactly
    the number of pages a candidate engine would serve from cache.
    Tokens hash as 4-byte little-endian; CRC32 keeps the export compact
    (one small int per cached page) and dependency-free."""
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    out: list[int] = []
    crc = 0
    for i in range(0, len(tokens) - len(tokens) % page_size, page_size):
        chunk = tokens[i : i + page_size]
        crc = zlib.crc32(struct.pack(f"<{len(chunk)}i", *chunk), crc)
        out.append(crc)
    return out


@dataclasses.dataclass
class _Node:
    """One cached page: ``tokens`` (exactly ``page_size`` of them) keyed
    under the parent, holding physical page ``page``."""

    tokens: tuple[int, ...]
    page: int
    parent: "_Node | None"
    children: dict[tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict
    )
    last_use: int = 0


class RadixCache:
    """Page-granular radix tree over prompt-token sequences.

    The tree owns one allocator reference per cached page; ``match``
    acquires an additional reference per matched page for the caller
    (released by the engine when the request retires or is evicted).
    """

    def __init__(self, page_size: int, allocator: PageAllocator) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._lock = make_lock("serving.radix")
        self.page_size = page_size
        self._alloc = allocator
        self._root: dict[tuple[int, ...], _Node] = {}
        self._clock = 0
        self._cached = 0
        # telemetry (tokens, not requests: a 3-page hit counts 3*ps)
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.hit_requests = 0
        self.lookup_requests = 0
        self.evicted_pages = 0

    @property
    def cached_pages(self) -> int:
        with self._lock:
            return self._cached

    def reset_stats(self) -> None:
        """Zero the hit/lookup/eviction telemetry (engine warmup flush);
        the tree itself is untouched."""
        with self._lock:
            self.hit_tokens = 0
            self.lookup_tokens = 0
            self.hit_requests = 0
            self.lookup_requests = 0
            self.evicted_pages = 0

    def hit_ratio(self) -> float:
        """Cumulative fraction of looked-up prompt tokens served from
        the cache (0.0 before any lookup)."""
        with self._lock:
            if self.lookup_tokens == 0:
                return 0.0
            return self.hit_tokens / self.lookup_tokens

    def _chunks(self, tokens: tuple[int, ...]) -> Iterator[tuple[int, ...]]:
        ps = self.page_size
        for i in range(0, len(tokens) - len(tokens) % ps, ps):
            yield tokens[i : i + ps]

    def match(
        self, tokens: tuple[int, ...], *, count: bool = True
    ) -> tuple[int, list[int]]:
        """Longest cached full-page prefix of ``tokens``: returns
        ``(matched_token_count, page_ids)`` with one allocator reference
        acquired per returned page (caller releases on retire/evict).

        The match is capped at ``len(tokens) - 1``: at least one real
        token must still prefill so the engine has last-position logits
        to sample the first generated token from.

        ``count=False`` skips the hit/lookup telemetry (the LRU clock
        still advances): the engine matches a page-starved pending head
        every iteration it stays blocked, and counting each retry would
        make the exported hit ratio stall-dependent — it records via
        :meth:`record_lookup` once the admission actually lands.
        """
        ps = self.page_size
        cap = (len(tokens) - 1) // ps  # full pages, leaving >= 1 token
        pages: list[int] = []
        with self._lock:
            self._clock += 1
            if count:
                self.lookup_requests += 1
                self.lookup_tokens += len(tokens)
            level = self._root
            for chunk in self._chunks(tokens):
                if len(pages) >= cap:
                    break
                node = level.get(chunk)
                if node is None:
                    break
                node.last_use = self._clock
                pages.append(node.page)
                level = node.children
            if pages and count:
                self.hit_requests += 1
                self.hit_tokens += len(pages) * ps
        if pages:
            self._alloc.share(pages)
        return len(pages) * ps, pages

    def record_lookup(self, looked_tokens: int, hit_tokens: int) -> None:
        """Telemetry for a ``match(count=False)`` whose admission
        succeeded: one lookup of ``looked_tokens``, ``hit_tokens`` of
        them served from the cache (0 for a clean miss)."""
        with self._lock:
            self.lookup_requests += 1
            self.lookup_tokens += looked_tokens
            if hit_tokens:
                self.hit_requests += 1
                self.hit_tokens += hit_tokens

    def pages(self) -> list[int]:
        """Every page id the tree currently holds a reference on (the
        engine's escalation gate feeds these to
        :meth:`~.pages.PageAllocator.freeable`)."""
        with self._lock:
            return [n.page for n in self._walk_all()]

    def insert(self, tokens: tuple[int, ...], pages: list[int]) -> int:
        """Cache the full pages of ``tokens`` (a retiring request's
        prompt): ``pages[i]`` holds tokens ``[i*ps, (i+1)*ps)``. Nodes
        already present are refreshed (their pages win — both copies are
        bitwise identical, so the newcomer's page simply keeps its
        engine reference and is freed normally); new nodes take one
        allocator reference each. Returns how many pages were newly
        adopted."""
        adopted: list[int] = []
        with self._lock:
            self._clock += 1
            level = self._root
            parent: _Node | None = None
            for i, chunk in enumerate(self._chunks(tokens)):
                if i >= len(pages):
                    break
                node = level.get(chunk)
                if node is None:
                    node = _Node(tokens=chunk, page=pages[i], parent=parent)
                    level[chunk] = node
                    adopted.append(pages[i])
                    self._cached += 1
                node.last_use = self._clock
                parent = node
                level = node.children
        if adopted:
            self._alloc.share(adopted)
        return len(adopted)

    def _leaves(self) -> list[_Node]:
        out: list[_Node] = []
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, n_pages: int) -> int:
        """Drop up to ``n_pages`` least-recently-used LEAF pages,
        releasing the tree's reference on each (the allocator recycles a
        page only once no live request shares it). Evicting a leaf can
        expose its parent for the next round; one call loops until the
        quota is met or the tree is empty. Returns pages released."""
        if n_pages <= 0:
            return 0
        released: list[int] = []
        with self._lock:
            while len(released) < n_pages:
                leaves = self._leaves()
                if not leaves:
                    break
                leaves.sort(key=lambda n: n.last_use)
                for node in leaves:
                    if len(released) >= n_pages:
                        break
                    if node.parent is None:
                        self._root.pop(node.tokens, None)
                    else:
                        node.parent.children.pop(node.tokens, None)
                    released.append(node.page)
                    self._cached -= 1
            self.evicted_pages += len(released)
        if released:
            self._alloc.release(released)
        return len(released)

    def clear(self) -> int:
        """Release every cached page (engine warmup flush)."""
        with self._lock:
            pages = [n.page for n in self._walk_all()]
            self._root = {}
            self._cached = 0
        if pages:
            self._alloc.release(pages)
        return len(pages)

    def fingerprints(self) -> list[int]:
        """Chained CRC32 fingerprints of every cached page path (the
        engine-side half of :func:`prefix_fingerprints`): one value per
        cached node, each committing to the full root-to-node token
        path. Exported through the metrics plane for the fleet router's
        prefix-affinity scoring; sorted for a deterministic wire doc."""
        out: list[int] = []
        with self._lock:
            stack: list[tuple[_Node, int]] = [
                (n, 0) for n in self._root.values()
            ]
            while stack:
                node, parent_crc = stack.pop()
                crc = zlib.crc32(
                    struct.pack(f"<{len(node.tokens)}i", *node.tokens),
                    parent_crc,
                )
                out.append(crc)
                stack.extend((c, crc) for c in node.children.values())
        return sorted(out)

    def _walk_all(self) -> list[_Node]:
        out: list[_Node] = []
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(n.children.values())
        return out
