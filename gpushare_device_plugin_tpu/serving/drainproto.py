"""The engine drain handshake, as a standalone protocol object.

This is the engine half of the live-defragmentation move protocol
(``allocator/defrag.py``): a mover thread asks the serving thread to
quiesce at its next iteration boundary, the serving thread captures its
unfinished requests into a JSON-safe snapshot, and the mover collects it
for restore on the destination slice. The state machine is three flips
guarded by one near-leaf lock:

- **arm** (:meth:`request`): reset any PRIOR cycle's answer, then raise
  the request flag. Only arming (and the everything-retired answer) may
  discard an uncollected capture — runs never do, so a snapshot survives
  back-to-back runs until its waiter reads it, however late that thread
  is scheduled.
- **capture** (:meth:`publish`): the serving thread, at an iteration
  boundary it observed :meth:`armed` at, stores the snapshot, lowers the
  request flag, and wakes the waiter.
- **consume** (:meth:`wait` / :meth:`snapshot`): the mover blocks for
  the capture. A timed-out wait DISARMS the drain before raising — the
  move is dead, and an engine left armed would quiesce its next
  unrelated run into a snapshot nobody collects (lost requests).
- **run end** (:meth:`finish_run`): a run that retired everything
  answers a concurrent drain with None (nothing to move) and disarms;
  an earlier cycle's uncollected capture is left for its waiter.

The class lives outside ``engine.py`` on purpose: it is pure protocol —
no jax, no pages — which lets ``tools/tpumc`` (the exhaustive-
interleaving model checker) drive the REAL handshake code against a
simulated serving loop and enumerate every arm/capture/consume/run-end
ordering, including the stale-answer and natural-end races the comments
below pin. Its lock and events are created through the ``lockrank``
factory seam, so under the checker every flip is a yield point.
``PagedSlotEngine`` composes it; the engine-facing methods
(``request_drain``/``wait_drained``/``drain_snapshot``) delegate here.
"""

from __future__ import annotations

from typing import Any

from ..utils.lockrank import make_event, make_lock


class DrainHandshake:
    """Arm/capture/consume state machine between one serving thread and
    one mover thread. Thread-safe; the lock is held around flag/dict
    flips only, a few times per run — never per tick, never over another
    lock (rank ``serving.drain``)."""

    def __init__(self) -> None:
        self._request_evt = make_event("serving.drain.request")
        self._drained_evt = make_event("serving.drain.drained")
        # serializes the arm/capture/consume transitions (near-leaf)
        self._lock = make_lock("serving.drain")
        self._snapshot: dict | None = None

    # --- mover side -------------------------------------------------------

    def request(self) -> None:
        """Arm: ask the in-progress run to quiesce at its next iteration
        boundary. Resets the quiesce state from any PRIOR run before
        arming: a completed run leaves the drained flag set (and possibly
        an old collected snapshot behind) — without this, a drain
        requested between runs returns that stale answer immediately and
        the NEXT run's capture is never collected (lost requests)."""
        with self._lock:
            self._drained_evt.clear()
            self._snapshot = None
            self._request_evt.set()

    def wait(self, timeout: float | None = None) -> dict | None:
        """Block until the serving thread quiesced after :meth:`request`
        — either it captured a snapshot or its run completed with
        nothing left in flight — then return :meth:`snapshot` (None in
        the ran-to-completion case). Raises ``TimeoutError`` when
        ``timeout`` (seconds) expires with no run reaching a boundary —
        the not-quiesced case MUST be distinguishable from the clean
        nothing-in-flight None: a mover that read None from a wedged
        engine would flip the pod's accounting while the source is still
        actively serving.

        A timed-out wait disarms before raising; if the serving thread
        reached the boundary in the instant between the wait expiring
        and the disarm, that capture is taken instead of raised away."""
        if not self._drained_evt.wait(timeout):
            with self._lock:
                if not self._drained_evt.is_set():
                    self._request_evt.clear()
                    raise TimeoutError(
                        "engine did not quiesce after request_drain()"
                        + (f" within {timeout}s" if timeout is not None else "")
                    )
        return self.snapshot()

    def snapshot(self) -> dict | None:
        """The snapshot captured by the last drained run (None when the
        last quiesce ended with everything retired; an uncollected
        capture survives back-to-back runs until the next
        :meth:`request` re-arms the cycle)."""
        return self._snapshot

    # --- serving side -----------------------------------------------------

    def armed(self) -> bool:
        """Whether a drain is requested (the run's iteration-boundary
        poll; cheap — one flag read, no lock)."""
        return self._request_evt.is_set()

    def publish(self, captured: dict) -> None:
        """Capture: store the quiesced run's snapshot, disarm, and wake
        the cross-thread :meth:`wait`."""
        with self._lock:
            self._snapshot = captured
            self._request_evt.clear()
            self._drained_evt.set()

    def finish_run(self) -> None:
        """Run completed naturally — quiesced either way: a drain
        requested after the last iteration boundary is CONSUMED by the
        everything-retired answer (flag set, snapshot None, drain
        disarmed — leaving it armed would make the next unrelated run
        quiesce into a snapshot nobody collects). Without the wake, a
        :meth:`wait` racing the run's natural end would block forever. A
        pending uncollected capture from an earlier drained run (flag
        already set) is left for its waiter."""
        with self._lock:
            if not self._drained_evt.is_set():
                self._snapshot = None
                self._request_evt.clear()
                self._drained_evt.set()

    # --- introspection ----------------------------------------------------

    def doc(self) -> dict[str, Any]:
        """Flag/snapshot state for debugging and the model checker's
        invariant checks."""
        with self._lock:
            return {
                "armed": self._request_evt.is_set(),
                "drained": self._drained_evt.is_set(),
                "has_snapshot": self._snapshot is not None,
            }
