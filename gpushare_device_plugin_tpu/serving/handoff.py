"""Disaggregated prefill/decode serving: the jax side of the KV handoff.

The protocol half — journal phases, import ledger, idempotent delivery
sink, retrying peer client, crash resolution — lives in
``handoffproto.py`` (jax-free, model-checked by ``tools/tpumc``,
SIGKILL-chaos'd by ``make chaos-handoff``). This module binds it to two
real :class:`~.engine.PagedSlotEngine` instances:

- **page serialization**: :func:`encode_page` / :func:`decode_page` turn
  one page's cache buffers (as fetched by
  :meth:`~.engine.PagedSlotEngine.export_kv_pages`) into wire bytes and
  back, checksummed per page by :func:`~.handoffproto.page_crc`;
- **:class:`DisaggServer`**: a two-tier serving plane — a PREFILL
  engine fills paged KV and produces each request's first token, then a
  :class:`~.handoffproto.HandoffMover` ships the pages to the DECODE
  engine through the journaled export→transfer→import→commit protocol;
  the decode engine adopts them straight into decode state (no second
  prefill) and streams the rest. A failed/timed-out transfer — or a
  prefill tier that is down entirely — degrades to local re-prefill on
  the decode tier: the request is never lost, and greedy determinism
  makes the tokens BIT-IDENTICAL to a unified engine either way (the
  parity tests and the ``serve_disagg`` bench gate exactly this, plus
  zero retraces).

Both engines keep their own tick clocks; the decode tier sees a
handed-off request arrive ``first_token_tick + transfer-delay`` ticks
into its own clock, so end-to-end TTFT reads off the prefill tier and
TPOT off the decode tier — the two pressures the SLO router scales
independently (docs/serving.md, disaggregation section).
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any, Mapping, Sequence

import numpy as np

from ..allocator.checkpoint import AllocationCheckpoint
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY, MetricsRegistry
from .engine import PagedSlotEngine, Request, ServeStats
from .handoffproto import (
    HandoffError,
    HandoffImportLedger,
    HandoffMover,
    HandoffPeerClient,
    HandoffPlan,
    HandoffSink,
)

log = get_logger("serving.handoff")

_HEADER = struct.Struct("<I")


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name round-tripped through ``str(arr.dtype)`` —
    plain numpy first, then the ml_dtypes extension types jax's low-
    precision caches use (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_page(blob: Mapping[str, np.ndarray]) -> bytes:
    """Serialize one exported page (dict of per-buffer numpy arrays) to
    wire bytes: a length-prefixed JSON header of ``[key, dtype, shape]``
    triples, then each buffer's raw bytes in header order. Keys are
    sorted so identical contents always serialize identically (the CRC
    the transfer checks is therefore content-deterministic)."""
    entries = []
    parts: list[bytes] = []
    for key in sorted(blob):
        arr = np.ascontiguousarray(blob[key])
        entries.append([key, str(arr.dtype), list(arr.shape)])
        parts.append(arr.tobytes())
    head = json.dumps(entries).encode("utf-8")
    return b"".join([_HEADER.pack(len(head)), head] + parts)


def decode_page(wire: bytes) -> dict[str, np.ndarray]:
    """Inverse of :func:`encode_page`. Raises ``ValueError`` on a
    malformed payload (truncated buffer, trailing bytes) — corruption
    the per-page CRC should have caught, so a raise here means the
    import degrades to re-prefill rather than adopting garbage."""
    if len(wire) < _HEADER.size:
        raise ValueError("page payload shorter than its header prefix")
    (hlen,) = _HEADER.unpack_from(wire, 0)
    off = _HEADER.size + hlen
    if off > len(wire):
        raise ValueError("page payload truncated inside its header")
    entries = json.loads(wire[_HEADER.size:off].decode("utf-8"))
    out: dict[str, np.ndarray] = {}
    for key, dtype_name, shape in entries:
        dtype = _np_dtype(dtype_name)
        size = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + size > len(wire):
            raise ValueError(f"page payload truncated in buffer {key!r}")
        out[str(key)] = np.frombuffer(
            wire[off:off + size], dtype=dtype
        ).reshape([int(d) for d in shape])
        off += size
    if off != len(wire):
        raise ValueError(f"{len(wire) - off} trailing bytes in page payload")
    return out


def build_handoff_plan(export: Mapping[str, Any], handoff_id: str) -> HandoffPlan:
    """Turn one engine export (:meth:`PagedSlotEngine._export_handoff`)
    into the mover's :class:`HandoffPlan`: pages serialized to wire
    bytes, request row and geometry meta carried as-is (they ride inside
    every journal record — the re-prefill guarantee)."""
    return HandoffPlan(
        handoff_id=handoff_id,
        request=dict(export["request"]),
        meta=dict(export["meta"]),
        pages=tuple(encode_page(b) for b in export["pages"]),
    )


class BrokenTransport:
    """A page-transfer path that is down: every verb raises. Wired in
    place of the in-process sink it forces the mover down the
    degradation ladder — the fallback delivery still reaches the decode
    tier over the control path — which is how the parity tests and the
    bench pin the re-prefill-is-lossless guarantee."""

    def stage(self, *a: Any, **k: Any) -> bool:
        raise HandoffError("transfer path down (injected)")

    def put_page(self, *a: Any, **k: Any) -> None:
        raise HandoffError("transfer path down (injected)")

    def deliver(self, *a: Any, **k: Any) -> str:
        raise HandoffError("transfer path down (injected)")

    def abort(self, *a: Any, **k: Any) -> bool:
        raise HandoffError("transfer path down (injected)")


class DisaggServer:
    """Two-tier serving plane over one prefill and one decode
    :class:`PagedSlotEngine` (same model params; geometry — eos, kv
    dtype, page size — must match for KV import, and a mismatch merely
    degrades to re-prefill).

    :meth:`serve` co-simulates the tiers: the prefill run exports each
    request at first-token time and the mover ships its pages inline
    (journaled when a ``checkpoint`` is supplied; degraded-unjournaled
    otherwise, like admissions on a sick disk); the decode run then
    serves every handed-off request, each arriving
    ``transfer-delay`` ticks after its prefill finished on the decode
    tier's own clock. ``transport`` overrides the page path (tests pass
    :class:`BrokenTransport` to force the fallback ladder); the control
    path — fallback delivery, dedup — always reaches the real sink.
    """

    def __init__(
        self,
        prefill: PagedSlotEngine,
        decode: PagedSlotEngine,
        *,
        checkpoint: AllocationCheckpoint | None = None,
        assume: Any = None,
        node: str = "local",
        transfer_pages_per_tick: int = 16,
        transport: Any = None,
        peer_kwargs: Mapping[str, Any] | None = None,
        registry: MetricsRegistry = REGISTRY,
        pod: str = "",
    ) -> None:
        if transfer_pages_per_tick < 1:
            raise ValueError(
                "transfer_pages_per_tick must be >= 1, got "
                f"{transfer_pages_per_tick}"
            )
        self.prefill = prefill
        self.decode = decode
        self._node = node
        self._xfer_rate = int(transfer_pages_per_tick)
        self._registry = registry
        self._pod = pod
        self.ledger = HandoffImportLedger()
        self.sink = HandoffSink(
            self.ledger,
            decode.allocator.alloc,
            decode.allocator.release,
            self._import_cb,
            self._reprefill_cb,
            registry=registry,
            pod=pod,
        )
        kw = dict(peer_kwargs or {})
        # co-simulated ticks, not wall clock: never really sleep between
        # retry attempts unless the caller asks for it
        kw.setdefault("sleep", lambda s: None)
        self.peer = HandoffPeerClient(
            transport if transport is not None else self.sink, **kw
        )
        self.mover = HandoffMover(
            checkpoint,
            assume,
            self.peer,
            fallback_fn=self.sink.deliver,
            node=node,
            registry=registry,
            pod=pod,
        )
        self._gen = 0
        # per-serve bookkeeping (reset by serve())
        self._exports: dict[int, dict] = {}
        self._deliveries: dict[int, dict] = {}
        self.outcomes: dict[str, int] = {}

    def warmup(self) -> None:
        self.prefill.warmup()
        self.decode.warmup()

    # --- decode-tier delivery callbacks (HandoffSink) ---------------------

    def _import_cb(
        self,
        pages: list[int],
        blobs: list[bytes],
        meta: dict,
        record: dict,
    ) -> None:
        eng = self.decode
        if (
            meta.get("page_size") != eng.page_size
            or meta.get("kv_dtype") != eng.kv_dtype
            or meta.get("eos_id") != eng.eos_id
        ):
            # adopting these pages would decode garbage or diverge the
            # token stream; a raise here makes the sink degrade to
            # re-prefill (which is geometry-independent)
            raise ValueError(
                f"handoff meta {meta} does not match decode engine "
                f"(page_size={eng.page_size}, kv_dtype={eng.kv_dtype}, "
                f"eos_id={eng.eos_id})"
            )
        row = record["request"]
        eng.import_kv_pages(pages, [decode_page(b) for b in blobs])
        eng.seed_handoff_import(
            int(row["rid"]),
            pages=pages,
            pos=int(meta["pos"]),
            last=int(row["tokens"][-1]),
            prompt=row["prompt"],
        )
        self._deliveries[int(row["rid"])] = {"mode": "imported", "row": row}

    def _reprefill_cb(self, record: dict) -> None:
        row = record["request"]
        self._deliveries[int(row["rid"])] = {"mode": "reprefill", "row": row}

    def _on_export(self, export: dict) -> None:
        rid = int(export["request"]["rid"])
        hid = f"{self._node}-g{self._gen}-r{rid}"
        self._exports[rid] = {
            "first_token_tick": int(export["first_token_tick"]),
            "n_pages": len(export["pages"]),
        }
        outcome = self.mover.execute(build_handoff_plan(export, hid))
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    # --- the two-tier co-simulation ---------------------------------------

    def serve(
        self,
        requests: Sequence[Request],
        *,
        prefill_down: bool = False,
    ) -> dict:
        """Serve ``requests`` across both tiers and return the combined
        per-request view::

            {
              "results": {rid: {"tokens", "ttft_ticks", "tpot_ticks",
                                "path"}},   # path: prefill|handoff|
                                            #   reprefill|prefill_down
              "outcomes": {...},  # mover outcomes this serve
              "dropped": [...],   # rids that never produced tokens
              "prefill": ServeStats | None, "decode": ServeStats,
            }

        ``prefill_down=True`` models a prefill-tier outage: every
        request is submitted raw to the decode tier (full local
        prefill) — the degradation ladder's floor, still bit-identical.
        """
        self._gen += 1
        self._exports = {}
        self._deliveries = {}
        self.outcomes = {}
        if prefill_down:
            dstats = self.decode.run(list(requests))
            results = {
                r.rid: self._entry(r, r.arrival_tick, "prefill_down")
                for r in dstats.results
            }
            return self._finish(requests, results, None, dstats)
        self.prefill.set_handoff_sink(self._on_export)
        try:
            pstats = self.prefill.run(list(requests))
        finally:
            self.prefill.set_handoff_sink(None)
        self.ledger.publish(self._registry, self._pod)
        by_rid = {r.rid: r for r in requests}
        decode_reqs: list[Request] = []
        seeds: dict[int, list[int]] = {}
        for rid, d in sorted(self._deliveries.items()):
            row = d["row"]
            exp = self._exports.get(rid) or {}
            delay = max(
                1,
                int(math.ceil(exp.get("n_pages", 1) / self._xfer_rate)),
            )
            arrival = float(exp.get("first_token_tick", 0) + delay)
            decode_reqs.append(
                Request(
                    rid=rid,
                    prompt=tuple(int(t) for t in row["prompt"]),
                    max_new=int(row["max_new"]),
                    arrival=arrival,
                    tier=str(row["tier"]),
                    slo_ttft_ticks=row.get("slo_ttft_ticks"),
                    slo_tpot_ticks=row.get("slo_tpot_ticks"),
                )
            )
            # every handed-off request starts from its prefill-tier
            # first token — the import path adopts KV on top of it, the
            # fallback path re-prefills prompt + token (bit-identical)
            seeds[rid] = [int(t) for t in row["tokens"]]
        self.decode.seed_restore_tokens(seeds)
        try:
            dstats = self.decode.run(decode_reqs)
        finally:
            self.decode.clear_handoff_seeds()
        results: dict[int, dict] = {}
        for r in pstats.results:
            if r.rid in self._deliveries:
                continue  # handed off; the decode tier's row is the result
            results[r.rid] = self._entry(r, r.arrival_tick, "prefill")
        darr = {q.rid: q.arrival for q in decode_reqs}
        for r in dstats.results:
            d = self._deliveries.get(r.rid)
            path = "handoff" if d and d["mode"] == "imported" else "reprefill"
            entry = self._entry(r, darr.get(r.rid, r.arrival_tick), path)
            exp = self._exports.get(r.rid)
            src = by_rid.get(r.rid)
            if exp is not None and src is not None:
                # end-to-end TTFT reads off the prefill tier's clock
                entry["ttft_ticks"] = (
                    exp["first_token_tick"] - float(src.arrival)
                )
            results[r.rid] = entry
        return self._finish(requests, results, pstats, dstats)

    def _entry(self, res: Any, start_tick: float, path: str) -> dict:
        n = len(res.tokens)
        ttft = (
            res.first_token_tick - float(start_tick)
            if res.first_token_tick is not None else None
        )
        tpot = (
            (res.finish_tick - float(start_tick)) / (n - 1)
            if n > 1 and res.finish_tick is not None else None
        )
        return {
            "tokens": list(res.tokens),
            "ttft_ticks": ttft,
            "tpot_ticks": tpot,
            "path": path,
        }

    def _finish(
        self,
        requests: Sequence[Request],
        results: dict[int, dict],
        pstats: ServeStats | None,
        dstats: ServeStats,
    ) -> dict:
        dropped = [
            r.rid for r in requests
            if r.rid not in results or not results[r.rid]["tokens"]
        ]
        if dropped:
            log.warning("disagg serve dropped rids %s", dropped)
        return {
            "results": results,
            "outcomes": dict(self.outcomes),
            "dropped": dropped,
            "prefill": pstats,
            "decode": dstats,
            "peer": self.peer.doc(),
        }
