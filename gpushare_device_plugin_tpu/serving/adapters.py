"""Refcounted LRU cache of paged LoRA adapters for the serving engine.

Thousands of per-tenant fine-tunes share ONE base model (ROADMAP open
item 2; the ParvaGPU spatial-sharing argument, PAPERS.md 2409.14447,
applied at the adapter level). The naive paths fragment the continuous
batch — ``merge_lora`` per tenant forks the weights, micro-batching per
adapter forks the dispatch — so instead adapters live as paged tensors
in the SAME refcounted :class:`~.pages.PageAllocator` pool as KV and
draft KV, and decode gathers each slot's adapter by page table
(``workloads/generate.py:lora_bgmv_views``): adapter identity is data,
never a shape.

This module is the host-side residency ledger:

- **One flat vector per adapter** (``workloads/lora.py:flatten_lora``),
  striped across ``pages_per_adapter`` pages of the shared pool. The
  ENGINE owns the device slab (``[total_pages + 1, page_size * d_model]``
  f32) and performs the actual row writes with this cache's lock
  released; the cache only decides which pages hold which adapter.
- **Pin while used, LRU when idle.** :meth:`acquire` pins an adapter for
  one slot (load-on-admission; the engine prefetches while the request
  waits in queue); :meth:`release` unpins at retire/preempt/drain. An
  unpinned adapter STAYS resident — the next request for it is a hit —
  until page pressure evicts it, least-recently-acquired first.
- **Below KV in the eviction ladder, SLO-tier-aware.** Adapter loads may
  self-evict other unpinned adapters but never touch the radix cache or
  preempt a request (adapters sit below KV: a cached prefix or a live
  row is always worth more than an idle adapter, which can be re-read
  from the store). KV allocation, conversely, reclaims idle adapters
  BEFORE radix pages (``engine._try_pages``). A best-effort requester
  cannot evict an adapter last used by a latency-critical request —
  the Tally-style tiered contention rule (PAPERS.md 2410.07381).

Pin counts are the adapter analog of the allocator's refcounts and are
deliberately private (the PR 6 double-booking lesson — tpulint's
ledger-encapsulation rule covers them): the allocator sees exactly ONE
reference per resident adapter page, held by this cache; slot pins
never touch allocator refcounts, so a pinned adapter simply refuses to
appear in :meth:`evictable` / :meth:`evict`.

Thread-safety: the engine loop acquires/releases; the ``/metrics``
scrape reads occupancy from another thread. Everything sits behind the
ranked ``serving.adapters`` lock (79), which allocates and releases
through ``serving.pages`` (87) while held — strictly up-rank, the
``serving.handoff`` precedent.
"""

from __future__ import annotations

import dataclasses

from .. import const
from ..utils.lockrank import make_lock
from ..utils.metric_catalog import (
    ENGINE_ADAPTER_CACHE_PAGES,
    ENGINE_ADAPTER_EVICTIONS_TOTAL,
    ENGINE_ADAPTER_HITS_TOTAL,
    ENGINE_ADAPTER_MISSES_TOTAL,
    ENGINE_ADAPTER_RESIDENT,
)
from ..utils.metrics import REGISTRY, MetricsRegistry
from .pages import PageAllocator


@dataclasses.dataclass
class _Entry:
    """One resident adapter: the slab pages holding its flat vector (in
    stripe order), how many live slots pin it, when it was last
    acquired, and whether a latency-critical request used it last (the
    tier shield best-effort eviction respects)."""

    pages: list[int]
    pins: int = 0
    last_use: int = 0
    critical: bool = False


class AdapterCache:
    """Host-side residency table: adapter id -> slab pages + pins.

    ``acquire`` returns ``(pages, loaded)`` — ``loaded=True`` means the
    pages are freshly allocated and the CALLER must write the adapter's
    flat vector into the device slab rows (in list order) before any
    slot decodes against it. ``None`` means the pool cannot hold the
    adapter even after evicting everything this requester's tier may
    touch — the engine leaves the request queued and retries.
    """

    def __init__(
        self, allocator: PageAllocator, pages_per_adapter: int
    ) -> None:
        if pages_per_adapter < 1:
            raise ValueError(
                f"pages_per_adapter must be >= 1, got {pages_per_adapter}"
            )
        self._lock = make_lock("serving.adapters")
        self._alloc = allocator
        self.pages_per_adapter = pages_per_adapter
        self._entries: dict[str, _Entry] = {}
        self._clock = 0
        # telemetry (cumulative; reset_stats zeroes for warmup)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- residency ----------------------------------------------------------

    @property
    def resident_count(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def cached_pages(self) -> int:
        with self._lock:
            return len(self._entries) * self.pages_per_adapter

    def resident(self, adapter_id: str) -> bool:
        with self._lock:
            return adapter_id in self._entries

    def pins(self, adapter_id: str) -> int:
        with self._lock:
            e = self._entries.get(adapter_id)
            return 0 if e is None else e.pins

    def pages_of(self, adapter_id: str) -> list[int] | None:
        """The adapter's slab pages in stripe order (None if absent) —
        what the engine turns into a slot's adapter page table."""
        with self._lock:
            e = self._entries.get(adapter_id)
            return None if e is None else list(e.pages)

    def pages(self) -> list[int]:
        """Every page the cache holds (pinned or not) — the engine's
        escalation gate subtracts these from what preemption could free."""
        with self._lock:
            return [p for e in self._entries.values() for p in e.pages]

    # -- pin lifecycle ------------------------------------------------------

    def acquire(
        self, adapter_id: str, *, tier: str = const.WORKLOAD_LATENCY_CRITICAL
    ) -> tuple[list[int], bool] | None:
        """Pin ``adapter_id`` for one slot, loading it if absent.

        Hit: bumps the pin count and LRU clock, returns
        ``(pages, False)``. Miss: allocates ``pages_per_adapter`` pages —
        evicting unpinned LRU adapters this ``tier`` may claim if the
        free list is short — and returns ``(pages, True)`` with the pin
        already taken; the caller writes the slab rows. ``None``: no
        capacity; nothing is counted (the engine retries each tick, and
        a stall must not inflate the miss rate — the miss is counted
        once, when the load lands)."""
        if not adapter_id:
            raise ValueError("adapter_id must be non-empty")
        critical = tier == const.WORKLOAD_LATENCY_CRITICAL
        with self._lock:
            self._clock += 1
            e = self._entries.get(adapter_id)
            if e is not None:
                e.pins += 1
                e.last_use = self._clock
                e.critical = e.critical or critical
                self.hits += 1
                return list(e.pages), False
            got = self._alloc.alloc(self.pages_per_adapter)
            while got is None:
                if not self._evict_one_locked(critical):
                    return None
                got = self._alloc.alloc(self.pages_per_adapter)
            self._entries[adapter_id] = _Entry(
                pages=got, pins=1, last_use=self._clock, critical=critical
            )
            self.misses += 1
            return list(got), True

    def release(self, adapter_id: str) -> None:
        """Unpin one slot's reference. The adapter stays resident (a
        future request is a hit) but becomes evictable at zero pins."""
        with self._lock:
            e = self._entries.get(adapter_id)
            if e is None or e.pins < 1:
                raise ValueError(
                    f"release of unpinned adapter {adapter_id!r}"
                )
            e.pins -= 1

    # -- eviction ladder ----------------------------------------------------

    def _victims_locked(self, critical: bool) -> list[tuple[str, _Entry]]:
        """Unpinned entries this requester tier may evict, LRU first.
        Best-effort requesters cannot claim adapters a latency-critical
        request used last (the tier shield)."""
        out = [
            (aid, e)
            for aid, e in self._entries.items()
            if e.pins == 0 and (critical or not e.critical)
        ]
        out.sort(key=lambda kv: kv[1].last_use)
        return out

    def _evict_one_locked(self, critical: bool) -> bool:
        victims = self._victims_locked(critical)
        if not victims:
            return False
        aid, e = victims[0]
        del self._entries[aid]
        self._alloc.release(e.pages)
        self.evictions += 1
        return True

    def evictable(
        self, *, tier: str = const.WORKLOAD_LATENCY_CRITICAL
    ) -> list[list[int]]:
        """Page groups (one per evictable adapter) a ``tier`` requester
        could reclaim — the :meth:`~.pages.PageAllocator.freeable` input
        for the engine's escalation gate."""
        critical = tier == const.WORKLOAD_LATENCY_CRITICAL
        with self._lock:
            return [list(e.pages) for _, e in self._victims_locked(critical)]

    def evict(
        self, n_pages: int, *, tier: str = const.WORKLOAD_LATENCY_CRITICAL
    ) -> int:
        """Evict unpinned LRU adapters (whole adapters — a half-resident
        adapter is useless) until at least ``n_pages`` pages went back to
        the free list or nothing ``tier`` may touch remains. Returns
        pages released. The engine's KV-allocation rung: idle adapters
        reclaim BEFORE radix pages and preemption."""
        if n_pages <= 0:
            return 0
        critical = tier == const.WORKLOAD_LATENCY_CRITICAL
        released = 0
        with self._lock:
            while released < n_pages:
                if not self._evict_one_locked(critical):
                    break
                released += self.pages_per_adapter
        return released

    def clear(self) -> int:
        """Release every UNPINNED adapter (engine warmup flush — warmup
        traffic must not pre-warm the measured hit ratio). Returns pages
        released; pinned adapters (live slots) stay."""
        with self._lock:
            victims = [
                (aid, e) for aid, e in self._entries.items() if e.pins == 0
            ]
            for aid, e in victims:
                del self._entries[aid]
                self._alloc.release(e.pages)
            return len(victims) * self.pages_per_adapter

    # -- telemetry ----------------------------------------------------------

    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0

    def reset_stats(self) -> None:
        """Zero hit/miss/eviction telemetry (engine warmup flush); the
        residency table is untouched."""
        with self._lock:
            self.hits = 0
            self.misses = 0
            self.evictions = 0

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "resident": len(self._entries),
                "cached_pages": len(self._entries) * self.pages_per_adapter,
                "pinned": sum(1 for e in self._entries.values() if e.pins),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_ratio": self.hits / total if total else 0.0,
            }

    def publish(
        self, registry: MetricsRegistry = REGISTRY, pod: str = ""
    ) -> None:
        """Export residency gauges (reads under the adapters lock, writes
        to the registry outside it — same discipline as
        :meth:`~.pages.PageAllocator.publish`). The engine publishes the
        hit/miss/eviction counters and the miss-stall histogram itself
        (delta-watermarked with its other families)."""
        with self._lock:
            resident = len(self._entries)
        labels = {"pod": pod} if pod else {}
        registry.gauge_set(
            ENGINE_ADAPTER_RESIDENT, resident,
            "LoRA adapters resident in the paged slab", **labels,
        )
        registry.gauge_set(
            ENGINE_ADAPTER_CACHE_PAGES, resident * self.pages_per_adapter,
            "Pool pages holding resident LoRA adapters", **labels,
        )


# Re-exported so callers needing only the counter names for parsing do
# not import the engine: the counter families the ENGINE publishes for
# this cache (see PagedSlotEngine._publish_adapters).
ADAPTER_COUNTER_FAMILIES = (
    ENGINE_ADAPTER_HITS_TOTAL,
    ENGINE_ADAPTER_MISSES_TOTAL,
    ENGINE_ADAPTER_EVICTIONS_TOTAL,
)
