"""Node chip-interconnect model: coordinate grids, sub-slice enumeration,
and multi-objective slice scoring for gang placement.

TPU pods expose their chips as a coordinate grid wired by ICI links
(v4/v5-style ``XxYxZ`` pod topologies: a 4-chip host is ``2x2x1``, an
8-chip host ``2x2x2``). Tensor-parallel collectives ride those links, so
*which* chips a multi-chip pod is granted decides whether its psums cross
one hop or crawl the mesh. A workload therefore claims a **shape**
(``"2x2x1"``), or a bare chip count (``"4"``) when any arrangement will
do, and the control plane picks the concrete sub-slice.

This module is the pure device-shape layer under that decision:

- :func:`parse_shape` / :func:`format_shape` — the ``"2x2x1"`` wire form
  used by the pod's gang-shape annotation and the node topology label;
- :class:`ChipTopology` — one node's grid: chip index <-> coordinates,
  ICI (Manhattan) distance, and enumeration of every axis-aligned
  sub-grid that realizes a requested shape (all axis orientations; for a
  bare count, all grid factorizations);
- :meth:`ChipTopology.best_slice` — score-ranked choice among the
  feasible candidates, jointly minimizing (in lexicographic order):

  1. **ICI hops** — the sum of pairwise chip distances inside the slice
     (a 2x2 square beats a 4x1 line: tighter collectives);
  2. **stranded slivers** — total HBM units left free on the member
     chips after the claim (best-fit: don't leave unusable crumbs);
  3. **broken whole chips** — how many previously-untouched chips the
     slice cracks open (fragmentation: prefer re-using partially-used
     chips so whole chips stay available for exclusive/core pods);
  4. lowest chip index — determinism.

  This is the multi-objective MIG-style placement trade (PAPERS.md,
  arXiv 2502.01909 — fragmentation, spread, and topology scored
  jointly) restricted to one node's grid; the extender applies it per
  node, the allocator re-applies it at admission under the reservation
  overlay.

Everything here is pure data + math: no apiserver, no ledger, no JAX.
The gang *claim* protocol lives in ``allocator/`` and ``extender/``;
the granted slice's mesh materialization lives in ``parallel/podenv.py``.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Iterable, Mapping, Sequence

MAX_DIMS = 3


def parse_shape(raw: str) -> tuple[int, ...]:
    """``"2x2x1"`` -> ``(2, 2, 1)``; a bare count ``"4"`` -> ``(4,)``.

    Raises ``ValueError`` on anything else (empty, zero/negative dims,
    more than three axes) — callers surface that as a filter/admission
    failure, never a crash.
    """
    parts = [p.strip() for p in str(raw).lower().split("x")]
    if not parts or len(parts) > MAX_DIMS:
        raise ValueError(f"invalid gang shape {raw!r}: expected up to 3 'x'-separated dims")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(f"invalid gang shape {raw!r}: non-integer dim") from None
    if any(d < 1 for d in dims):
        raise ValueError(f"invalid gang shape {raw!r}: dims must be >= 1")
    return dims


def format_shape(dims: Sequence[int]) -> str:
    return "x".join(str(d) for d in dims)


def shape_size(raw: str) -> int:
    """Chip count a shape string claims (``"2x2x1"`` -> 4, ``"4"`` -> 4)."""
    n = 1
    for d in parse_shape(raw):
        n *= d
    return n


def pad3(dims: Sequence[int]) -> tuple[int, int, int]:
    """Pad a 1-3 dim shape to the canonical (x, y, z) form — THE padding
    rule; the allocator's annotations and env payloads reuse it so the
    persisted shape and the injected carve-out can never diverge."""
    d = tuple(dims) + (1,) * (MAX_DIMS - len(dims))
    return d[0], d[1], d[2]


_pad3 = pad3


@dataclasses.dataclass(frozen=True)
class SliceCandidate:
    """One concrete sub-slice: the member chip indices (sorted), the
    realized shape, and its internal ICI cost (sum of pairwise Manhattan
    distances — the collective-traffic proxy the scorer minimizes)."""

    chips: tuple[int, ...]
    shape: tuple[int, int, int]
    hops: int


@dataclasses.dataclass(frozen=True)
class SliceScore:
    """The winning slice's multi-objective score components, in the
    lexicographic order :meth:`ChipTopology.best_slice` minimizes them:
    ICI hops, stranded slivers, broken whole chips, lowest-chip
    tie-break. Surfaced (rather than computed and discarded) so the
    decision-provenance layer can record *by what margin* a slice won —
    the policy-introspection seam pluggable placement policies
    implement."""

    hops: int
    stranded: int
    broken: int
    tie_break: int

    def to_dict(self) -> dict[str, int]:
        return {
            "ici_hops": self.hops,
            "stranded": self.stranded,
            "broken": self.broken,
            "tie_break": self.tie_break,
        }


class ChipTopology:
    """One node's chip grid. Chip index is row-major with x fastest:
    ``index = x + X*(y + Y*z)`` — matching the order discovery enumerates
    local devices, so index ``i`` here is local chip ``i`` everywhere
    else in the plugin."""

    def __init__(self, dims: Sequence[int]):
        x, y, z = _pad3(dims)
        if x < 1 or y < 1 or z < 1:
            raise ValueError(f"invalid topology dims {dims!r}")
        self.dims: tuple[int, int, int] = (x, y, z)

    def __repr__(self) -> str:
        return f"ChipTopology({format_shape(self.dims)})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ChipTopology) and self.dims == other.dims

    @property
    def n_chips(self) -> int:
        x, y, z = self.dims
        return x * y * z

    @classmethod
    def default_for(cls, n_chips: int) -> "ChipTopology":
        """The standard grid for a chip count: near-cubic powers of two
        (4 -> 2x2x1, 8 -> 2x2x2, 16 -> 4x2x2 — the v4/v5 host and slice
        shapes); anything else degrades to a line (``Nx1x1``)."""
        if n_chips < 1:
            raise ValueError(f"n_chips must be >= 1, got {n_chips}")
        dims = [1, 1, 1]
        rem, axis = n_chips, 0
        while rem % 2 == 0:
            dims[axis % MAX_DIMS] *= 2
            rem //= 2
            axis += 1
        dims[0] *= rem  # odd remainder stretches x
        return cls(sorted(dims, reverse=True))

    @classmethod
    def from_label(cls, label: str | None, n_chips: int) -> "ChipTopology":
        """Topology from the node's ``tpushare.aliyun.com/topology`` label
        when present and consistent with the advertised chip count; the
        default grid otherwise (a garbled label must degrade to sane
        placement, not wedge scheduling)."""
        if label:
            try:
                topo = cls(_pad3(parse_shape(label)))
                if topo.n_chips == n_chips:
                    return topo
            except ValueError:
                pass
        return cls.default_for(n_chips)

    @classmethod
    def from_node(cls, node: Mapping, n_chips: int) -> "ChipTopology":
        """THE label rule, in one place: read the topology label off a
        node's JSON and apply :meth:`from_label`. The extender, the
        daemon's allocator, and the inspect CLI all derive a node's grid
        through this one classmethod so they can never disagree about
        the same node's wiring."""
        from .. import const

        label = (node.get("metadata", {}).get("labels") or {}).get(
            const.LABEL_NODE_TOPOLOGY
        )
        return cls.from_label(label, n_chips)

    # --- coordinates ------------------------------------------------------

    def coords(self, index: int) -> tuple[int, int, int]:
        x_dim, y_dim, _ = self.dims
        if not 0 <= index < self.n_chips:
            raise ValueError(f"chip index {index} out of range for {self!r}")
        x = index % x_dim
        y = (index // x_dim) % y_dim
        z = index // (x_dim * y_dim)
        return x, y, z

    def index(self, x: int, y: int, z: int) -> int:
        x_dim, y_dim, _ = self.dims
        return x + x_dim * (y + y_dim * z)

    def distance(self, a: int, b: int) -> int:
        """ICI hop distance (Manhattan on the grid; single-host grids
        don't wrap — the torus closes only at full-pod dimensions)."""
        ca, cb = self.coords(a), self.coords(b)
        return sum(abs(i - j) for i, j in zip(ca, cb))

    def slice_hops(self, chips: Iterable[int]) -> int:
        members = list(chips)
        return sum(
            self.distance(a, b) for a, b in itertools.combinations(members, 2)
        )

    # --- sub-slice enumeration -------------------------------------------

    def _orientations(self, shape_raw: str) -> list[tuple[int, int, int]]:
        """Distinct 3-d orientations that realize ``shape_raw``: axis
        permutations of an explicit shape, every grid factorization of a
        bare count."""
        dims = parse_shape(shape_raw)
        if len(dims) == 1:
            n = dims[0]
            out = {
                (dx, dy, dz)
                for dx in range(1, n + 1)
                if n % dx == 0
                for dy in range(1, n // dx + 1)
                if (n // dx) % dy == 0
                for dz in [n // dx // dy]
            }
        else:
            out = set(itertools.permutations(_pad3(dims)))
        return sorted(out)

    def candidates(self, shape_raw: str) -> list[SliceCandidate]:
        """Every axis-aligned sub-grid realizing ``shape_raw``, deduped by
        chip set. Counts are small (a host grid has <= 16 chips), so the
        enumeration is exhaustive rather than clever."""
        seen: dict[tuple[int, ...], SliceCandidate] = {}
        X, Y, Z = self.dims
        for dx, dy, dz in self._orientations(shape_raw):
            if dx > X or dy > Y or dz > Z:
                continue
            for ox in range(X - dx + 1):
                for oy in range(Y - dy + 1):
                    for oz in range(Z - dz + 1):
                        chips = tuple(
                            sorted(
                                self.index(ox + i, oy + j, oz + k)
                                for i in range(dx)
                                for j in range(dy)
                                for k in range(dz)
                            )
                        )
                        if chips not in seen:
                            seen[chips] = SliceCandidate(
                                chips=chips,
                                shape=(dx, dy, dz),
                                hops=self.slice_hops(chips),
                            )
        return sorted(seen.values(), key=lambda c: (c.hops, c.chips))

    # --- scoring ----------------------------------------------------------

    def best_slice(
        self,
        shape_raw: str,
        free: Mapping[int, int],
        per_chip: int,
        *,
        capacity: Mapping[int, int] | None = None,
        excluded: Iterable[int] = (),
    ) -> SliceCandidate | None:
        """The best feasible sub-slice for ``shape_raw`` at ``per_chip``
        units per member chip, or None when nothing fits (the score-less
        convenience form of :meth:`best_slice_scored`).

        Feasible: every member chip has >= ``per_chip`` free units and is
        not in ``excluded`` (unhealthy / core-held chips). ``capacity``
        (chip -> total units) feeds the broken-whole-chip objective; when
        omitted, a chip whose free equals the max observed free is treated
        as whole.
        """
        scored = self.best_slice_scored(
            shape_raw, free, per_chip, capacity=capacity, excluded=excluded
        )
        return None if scored is None else scored[0]

    def best_slice_scored(
        self,
        shape_raw: str,
        free: Mapping[int, int],
        per_chip: int,
        *,
        capacity: Mapping[int, int] | None = None,
        excluded: Iterable[int] = (),
    ) -> tuple[SliceCandidate, SliceScore] | None:
        """:meth:`best_slice` plus the winner's :class:`SliceScore` —
        the objective components the ranking minimized, surfaced for
        decision provenance instead of discarded."""
        if per_chip < 0:
            raise ValueError(f"per_chip must be >= 0, got {per_chip}")
        banned = set(excluded)
        cap = dict(capacity) if capacity is not None else {}
        best: tuple | None = None
        best_cand: SliceCandidate | None = None
        for cand in self.candidates(shape_raw):
            if any(i in banned or free.get(i, 0) < per_chip for i in cand.chips):
                continue
            stranded = sum(free.get(i, 0) - per_chip for i in cand.chips)
            broken = sum(
                1
                for i in cand.chips
                if free.get(i, 0) == cap.get(i, free.get(i, 0))
                and free.get(i, 0) - per_chip > 0
            )
            key = (cand.hops, stranded, broken, cand.chips[0])
            if best is None or key < best:
                best, best_cand = key, cand
        if best_cand is None or best is None:
            return None
        return best_cand, SliceScore(
            hops=best[0], stranded=best[1], broken=best[2], tie_break=best[3]
        )
