from .model import (
    ChipTopology,
    SliceCandidate,
    SliceScore,
    format_shape,
    pad3,
    parse_shape,
    shape_size,
)

__all__ = [
    "ChipTopology",
    "SliceCandidate",
    "SliceScore",
    "format_shape",
    "pad3",
    "parse_shape",
    "shape_size",
]
