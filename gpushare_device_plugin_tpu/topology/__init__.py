from .model import (
    ChipTopology,
    SliceCandidate,
    format_shape,
    pad3,
    parse_shape,
    shape_size,
)

__all__ = [
    "ChipTopology",
    "SliceCandidate",
    "format_shape",
    "pad3",
    "parse_shape",
    "shape_size",
]
