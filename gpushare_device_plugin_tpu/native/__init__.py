"""Native (C++) helpers. See ``tpuinfo.py`` for the libtpu discovery shim."""
