/* libtpuinfo — TPU chip discovery shim, C ABI.
 *
 * TPU-native counterpart of the reference's NVML dlopen shim
 * (vendor/.../nvml_dl.c:21-27): the DaemonSet image must run on ANY node,
 * so libtpu is dlopen'd lazily and every capability degrades gracefully —
 * on a non-TPU node tpuinfo_init() succeeds with zero chips and the Go/C++
 * caller parks, mirroring gpumanager.go:36-47's wait-forever behavior.
 *
 * Discovery sources, in order:
 *   1. device files   <dev_root>/accel<N> (TPU-VM v4+) or <dev_root>/vfio/<N>
 *   2. sysfs          <sysfs_root>/class/accel/accel<N>/device/... (HBM, when
 *                     the accel driver exposes it)
 *   3. env            TPU_ACCELERATOR_TYPE / ACCELERATOR_TYPE generation
 *                     table, TPUSHARE_HBM_GIB override
 *   4. libtpu.so      liveness only (dlopen + symbol probe) — the runtime
 *                     health signal, the analog of NVML XID watching.
 *
 * Roots are overridable via TPUINFO_DEV_ROOT / TPUINFO_SYSFS_ROOT /
 * TPUINFO_LIBTPU_PATH so the whole shim is testable on any machine.
 */

#ifndef TPUSHARE_TPUINFO_H_
#define TPUSHARE_TPUINFO_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define TPUINFO_API __attribute__((visibility("default")))

#define TPUINFO_OK 0
#define TPUINFO_ERR_NOT_INITIALIZED -1
#define TPUINFO_ERR_BAD_INDEX -2

typedef struct tpuinfo_chip {
  int32_t index;          /* device number N of /dev/accel<N> (stable) */
  int64_t hbm_bytes;      /* total HBM; 0 = unknown */
  char device_path[512];  /* /dev/accel<N> or /dev/vfio/<N> */
  char id[64];            /* stable id keyed on N, e.g. "tpu-v5e-chip2" */
} tpuinfo_chip_t;

/* Scan devices, read metadata, lazily try libtpu. Never fails on a
 * TPU-less host; returns TPUINFO_OK with chip_count()==0. Idempotent. */
TPUINFO_API int tpuinfo_init(void);

/* Number of chips found by the last init/rescan. */
TPUINFO_API int tpuinfo_chip_count(void);

/* Fill *out for chip i. */
TPUINFO_API int tpuinfo_chip(int i, tpuinfo_chip_t* out);

/* HBM per chip in bytes (chips are homogeneous on a host); 0 = unknown. */
TPUINFO_API int64_t tpuinfo_hbm_bytes_per_chip(void);

/* 1 if the TPU runtime looks usable: libtpu loadable (when present) and
 * every discovered device file still exists. 0 otherwise. */
TPUINFO_API int tpuinfo_runtime_healthy(void);

/* 1 if libtpu.so was dlopen'd successfully. */
TPUINFO_API int tpuinfo_libtpu_loaded(void);

/* Re-scan device files (chips can appear after late driver init). */
TPUINFO_API int tpuinfo_rescan(void);

/* Last error string (static storage), "" if none. */
TPUINFO_API const char* tpuinfo_error(void);

/* Accelerator generation string, e.g. "v5e"; "" if unknown. */
TPUINFO_API const char* tpuinfo_generation(void);

TPUINFO_API void tpuinfo_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* TPUSHARE_TPUINFO_H_ */
