/* libtpuinfo implementation. See tpuinfo.h for the contract and
 * SURVEY.md section 2 ("Native components") for the reference mapping. */

#include "tpuinfo.h"

#include <dirent.h>
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>

#include <algorithm>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Generation {
  const char* name;
  int64_t hbm_bytes;
};

/* Public Cloud TPU per-chip HBM specs (mirrors discovery/tpuvm.py). */
const Generation kGenerations[] = {
    {"v2", 8LL << 30},   {"v3", 16LL << 30},       {"v4", 32LL << 30},
    {"v5e", 16LL << 30}, {"v5litepod", 16LL << 30}, {"v5p", 95LL << 30},
    {"v6e", 32LL << 30},
};

std::mutex g_mu;
bool g_initialized = false;
void* g_libtpu = nullptr;
bool g_libtpu_tried = false;
std::vector<tpuinfo_chip_t> g_chips;
int64_t g_hbm_bytes = 0;
char g_error[256] = "";
char g_generation[32] = "";

void set_error(const char* msg) {
  snprintf(g_error, sizeof(g_error), "%s", msg);
}

std::string env_or(const char* key, const char* fallback) {
  const char* v = getenv(key);
  return v && *v ? v : fallback;
}

/* "v5e-8" / "v4-32" -> generation prefix before the dash. */
std::string parse_generation() {
  std::string accel = env_or("TPU_ACCELERATOR_TYPE", "");
  if (accel.empty()) accel = env_or("ACCELERATOR_TYPE", "");
  size_t dash = accel.find('-');
  if (dash == std::string::npos) return "";
  return accel.substr(0, dash);
}

int64_t hbm_from_generation(const std::string& gen) {
  for (const auto& g : kGenerations)
    if (gen == g.name) return g.hbm_bytes;
  return 0;
}

/* Numeric suffix of "accel7" -> 7; -1 when the name doesn't match. */
int accel_index(const char* name, const char* prefix) {
  size_t plen = strlen(prefix);
  if (strncmp(name, prefix, plen) != 0) return -1;
  const char* digits = name + plen;
  if (!*digits) return -1;
  for (const char* p = digits; *p; ++p)
    if (*p < '0' || *p > '9') return -1;
  return atoi(digits);
}

/* Scan <root> for entries named <prefix><N>. Returns sorted indices. */
std::vector<int> scan_dir(const std::string& root, const char* prefix) {
  std::vector<int> found;
  DIR* d = opendir(root.c_str());
  if (!d) return found;
  while (struct dirent* e = readdir(d)) {
    int idx = accel_index(e->d_name, prefix);
    if (idx >= 0) found.push_back(idx);
  }
  closedir(d);
  std::sort(found.begin(), found.end());
  return found;
}

/* Read an integer out of a sysfs file; 0 on any failure. */
int64_t read_sysfs_int(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return 0;
  long long v = 0;
  int n = fscanf(f, "%lld", &v);
  fclose(f);
  return n == 1 && v > 0 ? (int64_t)v : 0;
}

void try_load_libtpu() {
  if (g_libtpu_tried) return;
  g_libtpu_tried = true;
  std::string path = env_or("TPUINFO_LIBTPU_PATH", "libtpu.so");
  /* Lazy, optional — the nvml_dl.c pattern: absence is not an error,
   * the host simply has no TPU runtime installed. */
  g_libtpu = dlopen(path.c_str(), RTLD_LAZY | RTLD_LOCAL);
}

int64_t discover_hbm(const std::string& sysfs_root, const std::vector<int>& chips,
                     bool accel_style) {
  /* 1. operator override */
  std::string override_gib = env_or("TPUSHARE_HBM_GIB", "");
  if (!override_gib.empty()) {
    long long gib = atoll(override_gib.c_str());
    if (gib > 0) return gib << 30;
  }
  /* 2. sysfs (accel driver), first chip: chips are homogeneous per host.
   * Only meaningful for accel-numbered devices — vfio group numbers do
   * not key /sys/class/accel. */
  if (accel_style && !chips.empty()) {
    char path[1024];
    snprintf(path, sizeof(path), "%s/class/accel/accel%d/device/hbm_bytes",
             sysfs_root.c_str(), chips[0]);
    int64_t v = read_sysfs_int(path);
    if (v > 0) return v;
  }
  /* 3. generation table */
  return hbm_from_generation(g_generation);
}

int rescan_locked() {
  std::string dev_root = env_or("TPUINFO_DEV_ROOT", "/dev");
  std::string sysfs_root = env_or("TPUINFO_SYSFS_ROOT", "/sys");
  std::string gen = parse_generation();
  snprintf(g_generation, sizeof(g_generation), "%s", gen.c_str());

  g_chips.clear();
  std::vector<int> indices = scan_dir(dev_root, "accel");
  bool accel_style = !indices.empty();
  const char* fmt = "%s/accel%d";
  if (indices.empty()) {
    indices = scan_dir(dev_root + "/vfio", "");
    fmt = "%s/vfio/%d";
  }
  g_hbm_bytes = discover_hbm(sysfs_root, indices, accel_style);
  for (size_t i = 0; i < indices.size(); ++i) {
    tpuinfo_chip_t chip;
    memset(&chip, 0, sizeof(chip));
    /* Key index and id on the device number, not the scan position:
     * sparse numbering (accel1 lost to a driver reset) must not renumber
     * the surviving chips across rescans. */
    chip.index = (int32_t)indices[i];
    chip.hbm_bytes = g_hbm_bytes;
    int n = snprintf(chip.device_path, sizeof(chip.device_path), fmt,
                     dev_root.c_str(), indices[i]);
    if (n < 0 || (size_t)n >= sizeof(chip.device_path)) {
      set_error("device path truncated (dev root too long)");
      g_chips.clear();
      return TPUINFO_ERR_BAD_INDEX;
    }
    snprintf(chip.id, sizeof(chip.id), "tpu-%s-chip%d",
             gen.empty() ? "unknown" : gen.c_str(), indices[i]);
    g_chips.push_back(chip);
  }
  return TPUINFO_OK;
}

}  // namespace

extern "C" {

int tpuinfo_init(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  try_load_libtpu();
  int rc = rescan_locked();
  g_initialized = (rc == TPUINFO_OK);
  return rc;
}

int tpuinfo_rescan(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (!g_initialized) {
    set_error("tpuinfo_rescan before tpuinfo_init");
    return TPUINFO_ERR_NOT_INITIALIZED;
  }
  return rescan_locked();
}

int tpuinfo_chip_count(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  return g_initialized ? (int)g_chips.size() : TPUINFO_ERR_NOT_INITIALIZED;
}

int tpuinfo_chip(int i, tpuinfo_chip_t* out) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (!g_initialized) {
    set_error("tpuinfo_chip before tpuinfo_init");
    return TPUINFO_ERR_NOT_INITIALIZED;
  }
  if (i < 0 || (size_t)i >= g_chips.size() || out == nullptr) {
    set_error("chip index out of range");
    return TPUINFO_ERR_BAD_INDEX;
  }
  *out = g_chips[i];
  return TPUINFO_OK;
}

int64_t tpuinfo_hbm_bytes_per_chip(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  return g_initialized ? g_hbm_bytes : 0;
}

int tpuinfo_runtime_healthy(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (!g_initialized) return 0;
  /* Health = every discovered device file still present. A vanished
   * /dev/accel<N> (driver reset, maintenance event) is the TPU analog of
   * an NVML XID critical event (nvidia.go:121-152). libtpu being loaded
   * is informative but not required: discovery must work in the plugin
   * container where only device files are mounted. */
  struct stat st;
  for (const auto& chip : g_chips)
    if (stat(chip.device_path, &st) != 0) return 0;
  return 1;
}

int tpuinfo_libtpu_loaded(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  return g_libtpu != nullptr ? 1 : 0;
}

const char* tpuinfo_error(void) { return g_error; }

const char* tpuinfo_generation(void) { return g_generation; }

void tpuinfo_shutdown(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (g_libtpu) {
    dlclose(g_libtpu);
    g_libtpu = nullptr;
  }
  g_libtpu_tried = false;
  g_chips.clear();
  g_initialized = false;
  g_error[0] = '\0';
}

}  /* extern "C" */
