"""ctypes loader for the libtpuinfo C++ shim (``tpuinfo.cpp``).

The Python side of the native boundary — analogous to the reference's cgo
``bindings.go`` over ``nvml_dl.c``. ``load()`` returns a :class:`NativeTpuInfo`
or raises; callers treat the shim as optional (``discovery/tpuvm.py``
falls back to pure-Python enumeration when loading fails), mirroring the
reference's build trick of linking with unresolved symbols allowed
(``Dockerfile:8``) so images run on driverless nodes.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
from pathlib import Path

ENV_LIBRARY = "TPUINFO_LIBRARY"
_DEFAULT_NAME = "libtpuinfo.so"


class _ChipStruct(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int32),
        ("hbm_bytes", ctypes.c_int64),
        ("device_path", ctypes.c_char * 512),
        ("id", ctypes.c_char * 64),
    ]


@dataclasses.dataclass(frozen=True)
class NativeChip:
    index: int
    hbm_bytes: int
    device_path: str
    id: str


class NativeTpuInfo:
    """Owned handle over an initialized libtpuinfo."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tpuinfo_init.restype = ctypes.c_int
        lib.tpuinfo_chip_count.restype = ctypes.c_int
        lib.tpuinfo_chip.restype = ctypes.c_int
        lib.tpuinfo_chip.argtypes = [ctypes.c_int, ctypes.POINTER(_ChipStruct)]
        lib.tpuinfo_hbm_bytes_per_chip.restype = ctypes.c_int64
        lib.tpuinfo_runtime_healthy.restype = ctypes.c_int
        lib.tpuinfo_libtpu_loaded.restype = ctypes.c_int
        lib.tpuinfo_rescan.restype = ctypes.c_int
        lib.tpuinfo_error.restype = ctypes.c_char_p
        lib.tpuinfo_generation.restype = ctypes.c_char_p
        rc = lib.tpuinfo_init()
        if rc != 0:
            raise OSError(f"tpuinfo_init failed: rc={rc} {self.error()}")

    def error(self) -> str:
        return (self._lib.tpuinfo_error() or b"").decode()

    def generation(self) -> str:
        return (self._lib.tpuinfo_generation() or b"").decode()

    def chip_count(self) -> int:
        return max(0, self._lib.tpuinfo_chip_count())

    def chips(self) -> list[NativeChip]:
        out = []
        for i in range(self.chip_count()):
            c = _ChipStruct()
            if self._lib.tpuinfo_chip(i, ctypes.byref(c)) == 0:
                out.append(
                    NativeChip(
                        index=c.index,
                        hbm_bytes=c.hbm_bytes,
                        device_path=c.device_path.decode(),
                        id=c.id.decode(),
                    )
                )
        return out

    def hbm_bytes_per_chip(self) -> int:
        return self._lib.tpuinfo_hbm_bytes_per_chip()

    def runtime_healthy(self) -> bool:
        return bool(self._lib.tpuinfo_runtime_healthy())

    def libtpu_loaded(self) -> bool:
        return bool(self._lib.tpuinfo_libtpu_loaded())

    def rescan(self) -> None:
        rc = self._lib.tpuinfo_rescan()
        if rc != 0:
            # A failed rescan clears the C-side chip list; surfacing the
            # error beats silently de-advertising every chip.
            raise OSError(f"tpuinfo_rescan failed: rc={rc} {self.error()}")

    def shutdown(self) -> None:
        self._lib.tpuinfo_shutdown()


def _candidates() -> list[str]:
    paths = []
    env = os.environ.get(ENV_LIBRARY)
    if env:
        paths.append(env)
    paths.append(str(Path(__file__).resolve().parent / _DEFAULT_NAME))
    paths.append(_DEFAULT_NAME)  # system search path
    return paths


def load(path: str | None = None) -> NativeTpuInfo:
    """Load + init libtpuinfo.

    An explicit ``path`` is authoritative (no fallback — a caller that
    names a library wants exactly that library); otherwise try
    ``$TPUINFO_LIBRARY``, the package dir, then the system search path.
    """
    if path:
        return NativeTpuInfo(ctypes.CDLL(path))
    last_err: Exception | None = None
    for cand in _candidates():
        try:
            return NativeTpuInfo(ctypes.CDLL(cand))
        except OSError as e:
            last_err = e
    raise OSError(f"libtpuinfo not loadable: {last_err}")
