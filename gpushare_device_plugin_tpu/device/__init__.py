from .fanout import (
    DeviceInventory,
    FakeDevice,
    extract_real_chip_id,
    generate_fake_device_id,
)

__all__ = [
    "DeviceInventory",
    "FakeDevice",
    "extract_real_chip_id",
    "generate_fake_device_id",
]
