"""Fake-device fan-out: the core resource-virtualization trick.

Kubernetes extended resources are opaque integers — kubelet cannot count
"GiB of HBM on chip 3". So one fake ``Device`` is advertised per memory
unit: a chip with 32 GiB HBM becomes 32 devices with IDs
``"<chipID>-_-<j>"`` (reference semantics: ``nvidia.go:26-31,75-87``).
A pod requesting ``aliyun.com/tpu-mem: 4`` is granted 4 fake IDs by
kubelet; ``Allocate()`` ignores which IDs and only counts them, then picks
the real chip itself.

Deliberate fix vs the reference: ``nvidia.go:71-74`` latches the *first*
GPU's memory as every device's capacity (implicit homogeneous assumption);
here capacity is tracked per chip.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

from ..const import MemoryUnit
from ..discovery.base import ChipHealth, TpuChip

FAKE_ID_SEP = "-_-"


def generate_fake_device_id(chip_id: str, unit_index: int) -> str:
    """Reference format ``%s-_-%d`` (``nvidia.go:26-28``)."""
    return f"{chip_id}{FAKE_ID_SEP}{unit_index}"


def extract_real_chip_id(fake_id: str) -> str:
    """Strip the unit suffix (``nvidia.go:30-31``)."""
    return fake_id.rsplit(FAKE_ID_SEP, 1)[0]


@dataclasses.dataclass(frozen=True)
class FakeDevice:
    id: str
    chip_id: str
    healthy: bool = True


class DeviceInventory:
    """Host inventory: chips, their unit capacities, and the fan-out lists."""

    def __init__(self, chips: Sequence[TpuChip], unit: MemoryUnit = MemoryUnit.GiB):
        self._unit = unit
        # single source of truth: chip id -> TpuChip (index/units derived),
        # plus the one inverse map needed for index lookups
        self._chips: dict[str, TpuChip] = {}
        self._id_by_index: dict[int, str] = {}
        for chip in sorted(chips, key=lambda c: c.index):
            if chip.id in self._chips:
                raise ValueError(f"duplicate chip id {chip.id!r}")
            if chip.index in self._id_by_index:
                raise ValueError(f"duplicate chip index {chip.index}")
            self._chips[chip.id] = chip
            self._id_by_index[chip.index] = chip.id

    # --- basic accessors ---------------------------------------------------

    @property
    def unit(self) -> MemoryUnit:
        return self._unit

    @property
    def chip_count(self) -> int:
        return len(self._chips)

    def chips(self) -> Sequence[TpuChip]:
        return sorted(self._chips.values(), key=lambda c: c.index)

    def chip_by_id(self, chip_id: str) -> TpuChip:
        return self._chips[chip_id]

    def index_of(self, chip_id: str) -> int:
        return self._chips[chip_id].index

    def id_of_index(self, index: int) -> str:
        """Inverse map, used to log the assigned chip (``server.go:76-87``)."""
        return self._id_by_index[index]

    def units_of(self, chip_id: str) -> int:
        """Memory units (= fake devices) on one chip."""
        return self._chips[chip_id].hbm_bytes // self._unit.num_bytes

    def units_by_index(self) -> Mapping[int, int]:
        """chip index -> total memory units; the binpack capacity vector."""
        return {c.index: self.units_of(c.id) for c in self._chips.values()}

    def total_units(self) -> int:
        return sum(self.units_of(cid) for cid in self._chips)

    # --- fan-out -----------------------------------------------------------

    def mem_fake_devices(
        self, health: Mapping[str, ChipHealth] | None = None
    ) -> list[FakeDevice]:
        """One fake device per memory unit, ordered by chip index then unit.

        ``health`` overrides the chips' discovered health (the live view kept
        by the health watcher).
        """
        out: list[FakeDevice] = []
        for chip in self.chips():
            h = (health or {}).get(chip.id, chip.health)
            ok = h == ChipHealth.HEALTHY
            out.extend(
                FakeDevice(
                    id=generate_fake_device_id(chip.id, j),
                    chip_id=chip.id,
                    healthy=ok,
                )
                for j in range(self.units_of(chip.id))
            )
        return out

    def core_devices(
        self, health: Mapping[str, ChipHealth] | None = None
    ) -> list[FakeDevice]:
        """One device per physical chip, for the whole-chip resource."""
        out = []
        for chip in self.chips():
            h = (health or {}).get(chip.id, chip.health)
            out.append(
                FakeDevice(id=chip.id, chip_id=chip.id, healthy=h == ChipHealth.HEALTHY)
            )
        return out
