"""Node-level apiserver interactions.

Reference: ``patchGPUCount`` (``podmanager.go:74-99``) — advertise the
physical chip count on node status — and ``disableCGPUIsolationOrNot``
(``podmanager.go:59-72``) — a node label acting as a feature flag for the
cooperative HBM cap.
"""

from __future__ import annotations

from .. import const
from ..utils.log import get_logger
from .apiserver import ApiServerClient

log = get_logger("cluster.node")


def patch_chip_count(client: ApiServerClient, node_name: str, count: int) -> None:
    """Write ``aliyun.com/tpu-count`` into node capacity, skipping no-ops."""
    node = client.get_node(node_name)
    status = node.get("status", {})
    current = status.get("capacity", {}).get(const.RESOURCE_COUNT)
    if current is not None and str(current) == str(count):
        log.v(4, "node %s already advertises %s=%d", node_name, const.RESOURCE_COUNT, count)
        return
    client.patch_node_status(node_name, {const.RESOURCE_COUNT: str(count)})
    log.info("patched node %s: %s=%d", node_name, const.RESOURCE_COUNT, count)


def isolation_disabled(client: ApiServerClient, node_name: str) -> bool:
    """Node label ``ctpu.disable.isolation=true`` disables the HBM cap."""
    try:
        node = client.get_node(node_name)
    except Exception as e:
        log.warning("node label read failed (%s); keeping isolation on", e)
        return False
    labels = node.get("metadata", {}).get("labels") or {}
    return labels.get(const.LABEL_DISABLE_ISOLATION) == "true"
