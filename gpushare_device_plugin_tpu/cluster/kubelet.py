"""Direct kubelet REST client: ``GET https://<node>:10250/pods/``.

Reference: ``pkg/kubelet/client/client.go:39-134`` — a bearer-token HTTPS
GET with TLS verification deliberately skipped (the kubelet serving cert is
rarely signed for the node IP; the reference strips the CA for the same
reason, ``client.go:79-83``). Returns the kubelet's authoritative local
pod list, which the Allocate path prefers for freshness when
``--query-kubelet`` is set.
"""

from __future__ import annotations

import urllib3
import requests

from ..utils.faults import FAULTS
from ..utils.log import get_logger

log = get_logger("cluster.kubelet")

urllib3.disable_warnings(urllib3.exceptions.InsecureRequestWarning)


class KubeletClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 10250,
        token: str = "",
        client_cert: tuple[str, str] | None = None,
        timeout_s: float = 10.0,
        scheme: str = "https",
    ) -> None:
        self.base_url = f"{scheme}://{host}:{port}"
        self._timeout = timeout_s
        self._session = requests.Session()
        self._session.verify = False  # kubelet serving certs: see module doc
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        if client_cert:
            self._session.cert = client_cert

    def get_node_running_pods(self) -> list[dict]:
        """The kubelet's local ``v1.PodList`` (``client.go:119-134``)."""
        FAULTS.fire("kubelet.pods")
        r = self._session.get(f"{self.base_url}/pods/", timeout=self._timeout)
        r.raise_for_status()
        return r.json().get("items", [])
