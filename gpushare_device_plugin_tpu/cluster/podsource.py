"""Pod sourcing for the Allocate path, with the reference's retry budgets.

Two backends (reference: ``podmanager.go:127-245``):
- kubelet ``/pods`` with 8 x 100 ms retries, falling back to the apiserver
  (``podmanager.go:141-157``) — fresher, preferred with ``--query-kubelet``;
- apiserver LIST with field selector
  ``spec.nodeName=<node>,status.phase=Pending`` and 3 x 1 s retries
  (``podmanager.go:159-176``).
"""

from __future__ import annotations

from typing import Protocol

from ..utils.log import get_logger
from ..utils.retry import RetryError, retry
from . import pods as P
from .apiserver import ApiServerClient
from .kubelet import KubeletClient

log = get_logger("cluster.podsource")

# Attempt counts keep the reference's budgets (``podmanager.go:143-147,
# 164-176``); the fixed delays became exponential backoff with full jitter
# plus a per-call deadline — these reads sit on the Allocate admission
# path, so a dead control plane must produce an error while kubelet still
# cares, and a recovering one must not be hit by synchronized retries.
KUBELET_RETRIES = 8
KUBELET_DELAY_S = 0.05
KUBELET_DEADLINE_S = 2.0
APISERVER_RETRIES = 3
APISERVER_DELAY_S = 0.25
APISERVER_DEADLINE_S = 5.0
_BACKOFF = dict(backoff=2.0, jitter=True)


def _apiserver_retry(fn):
    return retry(
        fn,
        attempts=APISERVER_RETRIES,
        delay_s=APISERVER_DELAY_S,
        deadline_s=APISERVER_DEADLINE_S,
        **_BACKOFF,
    )


class PodSource(Protocol):
    def pending_pods(self) -> list[dict]:
        """Pods on this node that may be awaiting allocation."""
        ...

    def pending_share_pods(self, resource: str) -> list[dict]:
        """Pending pods requesting ``resource`` — the allocator's match
        universe. List-backed sources filter a fresh pending LIST; the
        informer serves its pending-by-resource index (O(bucket))."""
        ...

    def running_share_pods(self) -> list[dict]:
        """Running pods bearing the tpushare label (usage accounting)."""
        ...

    def labeled_pods(self) -> list[dict]:
        """All pods bearing the tpu/resource label (either value) — one
        snapshot for cross-resource accounting per Allocate."""
        ...

    def refresh(self) -> None:
        """Make the next reads at least as fresh as the apiserver now.

        No-op for list-backed sources (every read is a fresh LIST); the
        informer uses it to close its watch-lag window on a match miss.
        """
        ...

    def note_pod_update(self, pod: dict) -> None:
        """Inform the source of a pod the caller just wrote (PATCH result)."""
        ...

    def evict(self, pod: dict) -> None:
        """Inform the source a pod is gone on the server (e.g. PATCH 404).

        No-op for list-backed sources; the informer drops it from its cache
        so a deleted pod can't shadow a live same-size candidate.
        """
        ...

    def chip_state(self) -> tuple[dict[int, int], set[int]]:
        """One consistent usage read for the Allocate path: -> (mem units
        used per chip, exclusively-held chips). List-backed sources derive
        it from a labeled-pods snapshot; the informer maintains it
        incrementally (O(chips) per admission)."""
        ...


def _chip_state_from(labeled_pods: list[dict]) -> tuple[dict[int, int], set[int]]:
    return P.used_units_by_chip(labeled_pods), P.used_chips(labeled_pods)


class ApiServerPodSource:
    def __init__(self, client: ApiServerClient, node_name: str) -> None:
        self._c = client
        self._node = node_name

    def refresh(self) -> None:
        pass  # every read LISTs — always fresh

    def note_pod_update(self, pod: dict) -> None:
        pass  # ditto

    def evict(self, pod: dict) -> None:
        pass  # nothing cached

    def pending_pods(self) -> list[dict]:
        return _apiserver_retry(
            lambda: self._c.list_pods(
                field_selector=f"spec.nodeName={self._node},status.phase=Pending"
            )
        )

    def pending_share_pods(self, resource: str) -> list[dict]:
        return [
            p
            for p in self.pending_pods()
            if P.mem_units_of_pod(p, resource=resource) > 0
        ]

    def running_share_pods(self) -> list[dict]:
        from .. import const

        return _apiserver_retry(
            lambda: self._c.list_pods(
                field_selector=f"spec.nodeName={self._node}",
                label_selector=f"{const.LABEL_RESOURCE_KEY}={const.LABEL_RESOURCE_VALUE}",
            )
        )

    def labeled_pods(self) -> list[dict]:
        from .. import const

        # existence selector: one LIST covers both resource values
        return _apiserver_retry(
            lambda: self._c.list_pods(
                field_selector=f"spec.nodeName={self._node}",
                label_selector=const.LABEL_RESOURCE_KEY,
            )
        )

    def chip_state(self) -> tuple[dict[int, int], set[int]]:
        return _chip_state_from(self.labeled_pods())


class KubeletPodSource:
    """Kubelet-first with apiserver fallback (``podmanager.go:141-157``)."""

    def __init__(
        self,
        kubelet: KubeletClient,
        fallback: ApiServerPodSource,
        node_name: str,
    ) -> None:
        self._kubelet = kubelet
        self._fallback = fallback
        self._node = node_name

    def refresh(self) -> None:
        pass  # every read hits kubelet/apiserver — always fresh

    def note_pod_update(self, pod: dict) -> None:
        pass  # ditto

    def evict(self, pod: dict) -> None:
        pass  # nothing cached

    def _kubelet_pods(self) -> list[dict]:
        return retry(
            self._kubelet.get_node_running_pods,
            attempts=KUBELET_RETRIES,
            delay_s=KUBELET_DELAY_S,
            deadline_s=KUBELET_DEADLINE_S,
            **_BACKOFF,
        )

    def pending_pods(self) -> list[dict]:
        try:
            pods = self._kubelet_pods()
        except RetryError as e:
            log.warning("kubelet /pods failed (%s); falling back to apiserver", e)
            return self._fallback.pending_pods()
        # kubelet reports all local pods; keep the pending ones
        return [p for p in pods if P.phase(p) == "Pending"]

    def pending_share_pods(self, resource: str) -> list[dict]:
        return [
            p
            for p in self.pending_pods()
            if P.mem_units_of_pod(p, resource=resource) > 0
        ]

    def running_share_pods(self) -> list[dict]:
        from .. import const

        try:
            pods = self._kubelet_pods()
        except RetryError:
            return self._fallback.running_share_pods()
        return [
            p
            for p in pods
            if P.labels(p).get(const.LABEL_RESOURCE_KEY) == const.LABEL_RESOURCE_VALUE
        ]

    def labeled_pods(self) -> list[dict]:
        from .. import const

        try:
            pods = self._kubelet_pods()
        except RetryError:
            return self._fallback.labeled_pods()
        return [p for p in pods if const.LABEL_RESOURCE_KEY in P.labels(p)]

    def chip_state(self) -> tuple[dict[int, int], set[int]]:
        return _chip_state_from(self.labeled_pods())
