"""Incremental chip-usage accounting over an informer cache.

The reference recomputes usage by scanning every labeled pod on each
Allocate (``getPodUsedGPUMemory``, ``podmanager.go:102-115`` — a LIST plus
an O(pods) walk per admission). Round 2 moved the LIST into the watch cache
but kept the O(pods) walk; this module removes the walk too: a
``NodeChipUsage`` index subscribes to cache mutations and maintains the two
aggregates the Allocate path reads — fractional HBM units used per chip and
the set of exclusively-held chips — so each admission reads O(chips), not
O(pods).

Correctness contract: a pod's contribution is a pure function of its JSON
(``_mem_contribution`` / ``_core_contribution``, built on the same
``cluster.pods`` predicates the batch helpers use), so applying
``on_change(old, new)`` as subtract-then-add keeps the aggregates exactly
equal to ``P.used_units_by_chip(cache)`` / ``P.used_chips(cache)`` at every
point. Exclusive holds are reference-counted: two pods claiming one chip is
an anomaly the allocator rejects, but the index must not forget the
surviving hold when one of them dies.
"""

from __future__ import annotations


from .. import const
from . import pods as P
from ..utils.lockrank import make_lock


def _mem_contributions(pod: dict) -> list[tuple[int, int]]:
    """[(chip index, units)] this pod adds to fractional-HBM accounting
    ([] when none) — the per-pod form of ``P.used_units_by_chip``. A
    multi-chip gang contributes its per-chip share on EVERY member chip;
    a single-chip pod its total on its IDX chip."""
    if not P.is_active(pod):
        return []
    if P.labels(pod).get(const.LABEL_RESOURCE_KEY) != const.LABEL_RESOURCE_VALUE:
        return []
    if not P.is_assigned(pod):
        return []
    gang = P.gang_usage_by_chip(pod)
    if gang:
        return sorted(gang.items())
    idx = P.chip_idx_from_annotation(pod)
    if idx < 0:
        return []
    return [(idx, P.mem_units_of_pod(pod))]


def _core_contribution(pod: dict) -> list[int]:
    """Chips this pod holds exclusively — the per-pod form of
    ``P.used_chips``."""
    if not P.is_active(pod):
        return []
    if not P.is_assigned(pod):
        return []
    return P.core_hold_chips(pod)


def pod_counts_toward_usage(pod: dict) -> bool:
    """True when this pod's JSON contributes to either aggregate — i.e. a
    cache holding this copy already accounts for it. The allocator's
    reservation overlay uses this to stop counting an in-flight pod the
    moment its PATCHed copy lands in the pod source."""
    return bool(_mem_contributions(pod)) or bool(_core_contribution(pod))


class NodeChipUsage:
    """Per-chip usage aggregates for one node's pods (the daemon's view)."""

    def __init__(self) -> None:
        self._lock = make_lock("cluster.usage")
        self._mem_used: dict[int, int] = {}
        self._core_refs: dict[int, int] = {}
        # per-chip resident share pods and their workload classes — the
        # interference detector's co-residency input (a gang pod resides
        # on every member chip). Keyed (namespace, name) -> class.
        self._residents: dict[int, dict[tuple[str, str], str]] = {}

    # --- informer index protocol -----------------------------------------

    def rebuild(self, pods: list[dict]) -> None:
        with self._lock:
            self._mem_used.clear()
            self._core_refs.clear()
            self._residents.clear()
            for pod in pods:
                self._add(pod)

    def on_change(self, old: dict | None, new: dict | None) -> None:
        with self._lock:
            if old is not None:
                self._remove(old)
            if new is not None:
                self._add(new)

    # --- internals (lock held) -------------------------------------------

    def _add(self, pod: dict) -> None:
        key = (P.namespace(pod), P.name(pod))
        cls = P.workload_class(pod)
        for idx, units in _mem_contributions(pod):
            self._mem_used[idx] = self._mem_used.get(idx, 0) + units
            self._residents.setdefault(idx, {})[key] = cls
        for idx in _core_contribution(pod):
            self._core_refs[idx] = self._core_refs.get(idx, 0) + 1

    def _remove(self, pod: dict) -> None:
        key = (P.namespace(pod), P.name(pod))
        for idx, units in _mem_contributions(pod):
            left = self._mem_used.get(idx, 0) - units
            if left > 0:
                self._mem_used[idx] = left
            else:
                self._mem_used.pop(idx, None)
            members = self._residents.get(idx)
            if members is not None:
                members.pop(key, None)
                if not members:
                    self._residents.pop(idx, None)
        for idx in _core_contribution(pod):
            left = self._core_refs.get(idx, 0) - 1
            if left > 0:
                self._core_refs[idx] = left
            else:
                self._core_refs.pop(idx, None)

    # --- reads ------------------------------------------------------------

    def snapshot(self) -> tuple[dict[int, int], set[int]]:
        """-> (mem units used per chip, exclusively-held chips)."""
        with self._lock:
            return dict(self._mem_used), set(self._core_refs)

    def residency(self) -> dict[int, dict[str, str]]:
        """Per-chip resident share pods and their workload classes:
        chip -> {"ns/name": class} — the interference detector's
        co-residency input (``cluster/interference.py``), maintained
        incrementally like the unit aggregates."""
        with self._lock:
            return {
                idx: {f"{ns}/{name}": cls for (ns, name), cls in members.items()}
                for idx, members in self._residents.items()
            }
