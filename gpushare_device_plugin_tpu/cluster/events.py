"""Kubernetes Event emission for allocation/bind failures.

The reference's RBAC grants ``create events`` (``device-plugin-rbac.yaml:
8-37``) but its code never uses it — failures are glog-only and operators
must read node logs to learn why admission failed. Surfacing them as
Warning events on the pod makes ``kubectl describe pod`` show the cause.
Best-effort by design: an event that cannot be posted must never turn a
clean failure path into a crash.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any

from ..utils.log import get_logger

log = get_logger("cluster.events")

COMPONENT = "tpushare-device-plugin"
REASON_ALLOC_FAILED = "TpuShareAllocationFailed"
REASON_BIND_FAILED = "TpuShareBindFailed"
REASON_CHIP_UNHEALTHY = "TpuChipUnhealthy"
REASON_CHIP_RECOVERED = "TpuChipRecovered"
REASON_CHIP_APP_FAULT = "TpuChipAppLevelFault"
REASON_CHIP_TRANSIENT = "TpuChipTransientBlip"


def _post_event(
    api,
    namespace: str,
    involved: dict,
    reason: str,
    message: str,
    component: str,
    host: str,
    event_type: str,
) -> bool:
    """Shared best-effort Event POST (one schema for pod + node events).
    Returns False when the post failed (callers that count drops care;
    fire-and-forget callers ignore it)."""
    name = involved.get("name", "")
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    event = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "generateName": f"{name}.tpushare-" if name else "tpushare-",
            "namespace": namespace,
        },
        "involvedObject": involved,
        "reason": reason,
        "message": message,
        "type": event_type,
        "source": {"component": component, "host": host},
        "firstTimestamp": now,
        "lastTimestamp": now,
        "count": 1,
    }
    try:
        api.create_event(namespace, event)
    except Exception as e:  # noqa: BLE001 — events are best-effort
        log.warning(
            "event emission failed for %s %s: %s",
            involved.get("kind", "?"), name, e,
        )
        return False
    return True


def emit_node_event(
    api: Any,
    node_name: str,
    reason: str,
    message: str,
    *,
    component: str = COMPONENT,
    event_type: str = "Warning",
) -> bool:
    """Warning/Normal event on the Node object so ``kubectl describe node``
    shows chip health transitions with their classified reason (the
    reference's XID events were glog-only)."""
    return _post_event(
        api, "default",
        {"apiVersion": "v1", "kind": "Node", "name": node_name, "uid": node_name},
        reason, message, component, node_name, event_type,
    )


def emit_pod_event(
    api: Any,
    pod: dict,
    reason: str,
    message: str,
    *,
    component: str = COMPONENT,
    host: str = "",
    event_type: str = "Warning",
) -> None:
    meta = pod.get("metadata", {}) if pod else {}
    ns = meta.get("namespace", "default")
    _post_event(
        api, ns,
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "namespace": ns,
            "name": meta.get("name", ""),
            "uid": meta.get("uid", ""),
        },
        reason, message, component, host, event_type,
    )


class NodeEventEmitter:
    """One worker + one bounded queue for node health events.

    Replaces the thread-per-event emission (a 5 s health poll against an
    unreachable apiserver used to spawn a fresh daemon thread per event,
    each parked on a connect timeout — unbounded thread growth for the
    whole outage). The queue bounds memory; a full queue drops the oldest
    behavior by refusing the newest and counting it — during an outage the
    event's value decays fast anyway, and the health state itself lives in
    ListAndWatch/allocator, not in Events.
    """

    def __init__(self, api: Any, node_name: str, maxsize: int = 64) -> None:
        self._api = api
        self._node = node_name
        self._q: "queue.Queue[tuple[str, str, str] | None]" = queue.Queue(maxsize)
        self._thread: threading.Thread | None = None

    def start(self) -> "NodeEventEmitter":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="node-events"
        )
        self._thread.start()
        return self

    def _count_drop(self, why: str) -> None:
        from ..utils.metric_catalog import NODE_EVENTS_DROPPED_TOTAL
        from ..utils.metrics import REGISTRY

        REGISTRY.counter_inc(
            NODE_EVENTS_DROPPED_TOTAL,
            "Node events dropped (full queue or failed send)",
            reason=why,
        )

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            reason, message, event_type = item
            if not emit_node_event(
                self._api, self._node, reason, message, event_type=event_type
            ):
                self._count_drop("send_failed")

    def emit(self, reason: str, message: str, event_type: str = "Warning") -> None:
        """Non-blocking enqueue; never stalls the health watcher."""
        try:
            self._q.put_nowait((reason, message, event_type))
        except queue.Full:
            self._count_drop("queue_full")

    def stop(self) -> None:
        if self._thread is None:
            return
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker is behind; it is a daemon thread, let it go
        self._thread.join(timeout=2.0)
        self._thread = None
