"""Kubernetes Event emission for allocation/bind failures.

The reference's RBAC grants ``create events`` (``device-plugin-rbac.yaml:
8-37``) but its code never uses it — failures are glog-only and operators
must read node logs to learn why admission failed. Surfacing them as
Warning events on the pod makes ``kubectl describe pod`` show the cause.
Best-effort by design: an event that cannot be posted must never turn a
clean failure path into a crash.
"""

from __future__ import annotations

import time

from ..utils.log import get_logger

log = get_logger("cluster.events")

COMPONENT = "tpushare-device-plugin"
REASON_ALLOC_FAILED = "TpuShareAllocationFailed"
REASON_BIND_FAILED = "TpuShareBindFailed"
REASON_CHIP_UNHEALTHY = "TpuChipUnhealthy"
REASON_CHIP_RECOVERED = "TpuChipRecovered"
REASON_CHIP_APP_FAULT = "TpuChipAppLevelFault"
REASON_CHIP_TRANSIENT = "TpuChipTransientBlip"


def _post_event(
    api,
    namespace: str,
    involved: dict,
    reason: str,
    message: str,
    component: str,
    host: str,
    event_type: str,
) -> None:
    """Shared best-effort Event POST (one schema for pod + node events)."""
    name = involved.get("name", "")
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    event = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "generateName": f"{name}.tpushare-" if name else "tpushare-",
            "namespace": namespace,
        },
        "involvedObject": involved,
        "reason": reason,
        "message": message,
        "type": event_type,
        "source": {"component": component, "host": host},
        "firstTimestamp": now,
        "lastTimestamp": now,
        "count": 1,
    }
    try:
        api.create_event(namespace, event)
    except Exception as e:  # noqa: BLE001 — events are best-effort
        log.warning(
            "event emission failed for %s %s: %s",
            involved.get("kind", "?"), name, e,
        )


def emit_node_event(
    api,
    node_name: str,
    reason: str,
    message: str,
    *,
    component: str = COMPONENT,
    event_type: str = "Warning",
) -> None:
    """Warning/Normal event on the Node object so ``kubectl describe node``
    shows chip health transitions with their classified reason (the
    reference's XID events were glog-only)."""
    _post_event(
        api, "default",
        {"apiVersion": "v1", "kind": "Node", "name": node_name, "uid": node_name},
        reason, message, component, node_name, event_type,
    )


def emit_pod_event(
    api,
    pod: dict,
    reason: str,
    message: str,
    *,
    component: str = COMPONENT,
    host: str = "",
    event_type: str = "Warning",
) -> None:
    meta = pod.get("metadata", {}) if pod else {}
    ns = meta.get("namespace", "default")
    _post_event(
        api, ns,
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "namespace": ns,
            "name": meta.get("name", ""),
            "uid": meta.get("uid", ""),
        },
        reason, message, component, host, event_type,
    )
