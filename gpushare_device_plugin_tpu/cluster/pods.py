"""Pod-state predicates and accessors over k8s pod JSON.

Pure functions over the JSON wire form of ``v1.Pod`` (we use no kubernetes
client library; both the apiserver and kubelet clients hand back parsed
JSON). Mirrors the reference's ``podutils.go:38-136`` predicates and the
candidate/used-memory accounting in ``podmanager.go:102-293``.

Pod lifecycle as seen by the plugin (the "apiserver is the database" state
machine):

  Pending ──(extender assumes: writes IDX + ASSUME_TIME)──▶ assumed
  Pending/assumed ──(Allocate(): writes ASSIGNED=true ...)──▶ assigned
  Running(label tpu/resource=tpu-mem + IDX annotation) ──▶ counted as usage
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

from .. import const

Pod = Mapping[str, Any]  # parsed v1.Pod JSON


# --- metadata accessors ----------------------------------------------------


def name(pod: Pod) -> str:
    return pod.get("metadata", {}).get("name", "")


def namespace(pod: Pod) -> str:
    return pod.get("metadata", {}).get("namespace", "default")


def uid(pod: Pod) -> str:
    return pod.get("metadata", {}).get("uid", "")


def annotations(pod: Pod) -> Mapping[str, str]:
    return pod.get("metadata", {}).get("annotations") or {}


def labels(pod: Pod) -> Mapping[str, str]:
    return pod.get("metadata", {}).get("labels") or {}


def node_name(pod: Pod) -> str:
    return pod.get("spec", {}).get("nodeName", "")


def phase(pod: Pod) -> str:
    return pod.get("status", {}).get("phase", "")


def creation_timestamp(pod: Pod) -> str:
    # RFC3339 strings sort lexicographically in time order
    return pod.get("metadata", {}).get("creationTimestamp", "")


def sort_key_by_creation(pod: Pod) -> tuple[str, str, str]:
    """Oldest first; name/namespace tiebreak for determinism.

    The reference sorts by CreationTimestamp only (``podmanager.go:281-293``)
    which leaves same-instant pods in arbitrary order — one of the two sides
    of the documented allocation race (SURVEY.md section 3.2); the tiebreak
    removes the nondeterminism on our side.
    """
    return (creation_timestamp(pod), namespace(pod), name(pod))


# --- resource accounting ---------------------------------------------------


def _quantity(v: Any) -> int:
    """Parse an extended-resource quantity (always a bare integer)."""
    try:
        return int(str(v))
    except (TypeError, ValueError):
        return 0


def mem_units_of_container(container: Mapping[str, Any], resource: str = const.RESOURCE_MEM) -> int:
    limits = container.get("resources", {}).get("limits") or {}
    return _quantity(limits.get(resource))


def mem_units_of_pod(pod: Pod, resource: str = const.RESOURCE_MEM) -> int:
    """Sum of ``aliyun.com/tpu-mem`` container *limits* (``podutils.go:127-136``)."""
    return sum(
        mem_units_of_container(c, resource)
        for c in pod.get("spec", {}).get("containers", [])
    )


def core_chips_of_pod(pod: Pod) -> int:
    return mem_units_of_pod(pod, resource=const.RESOURCE_CORE)


# --- share-pod state predicates (podutils.go:84-124) -----------------------


def is_tpu_share_pod(pod: Pod) -> bool:
    return mem_units_of_pod(pod) > 0


def is_tpu_core_pod(pod: Pod) -> bool:
    return core_chips_of_pod(pod) > 0


def is_assumed(pod: Pod) -> bool:
    """The scheduler extender wrote an assume-time annotation."""
    return const.ENV_ASSUME_TIME in annotations(pod)


def is_assigned(pod: Pod) -> bool:
    """Plugin has completed Allocate() for this pod.

    Reference semantics (``podutils.go:108-124``): the annotation must be
    present AND not literally "false".
    """
    v = annotations(pod).get(const.ENV_ASSIGNED_FLAG)
    return v is not None and v != "false"


def chip_idx_from_annotation(pod: Pod) -> int:
    """Assigned chip index, -1 when absent/garbled (``podutils.go:38-62``)."""
    v = annotations(pod).get(const.ENV_MEM_IDX)
    if v is None:
        return -1
    try:
        return int(v)
    except ValueError:
        return -1


def core_ids_from_annotation(pod: Pod) -> list[int]:
    """Chip indices exclusively held by this pod (``ENV_CORE_IDS``), []
    when absent/garbled."""
    v = annotations(pod).get(const.ENV_CORE_IDS)
    if not v:
        return []
    out: list[int] = []
    for part in str(v).split(","):
        try:
            out.append(int(part))
        except ValueError:
            return []
    return out


def gang_shape_request(pod: Pod) -> str:
    """The gang shape this pod ASKS for (``ANN_GANG_SHAPE`` on its spec:
    "2x2x1" or a bare count "4"), "" for ordinary single-chip pods. The
    request annotation is user-written; validity is checked where it is
    consumed (extender filter, allocator placement)."""
    return str(annotations(pod).get(const.ANN_GANG_SHAPE, "") or "")


def is_gang_pod(pod: Pod) -> bool:
    return bool(gang_shape_request(pod)) and mem_units_of_pod(pod) > 0


def gang_group(pod: Pod) -> str:
    """The pod's gang-GROUP id (``ANN_GANG_GROUP``), "" for pods that
    are not members of a cross-node group. Members of one group are
    admitted all-or-nothing through the sharded extender's two-phase
    reserve (extender/shards.py)."""
    return str(annotations(pod).get(const.ANN_GANG_GROUP, "") or "")


def serving_tier(pod: Pod) -> str:
    """The pod's disaggregated-serving tier (``ANN_SERVING_TIER``:
    "prefill" or "decode"), "" for unified serving pods or unknown
    values. One helper so group admission, the inspect CLI's TIER
    column, and `inspect why`'s two-tier composition can never disagree
    about which side of the KV handoff a member serves."""
    v = str(annotations(pod).get(const.ANN_SERVING_TIER, "") or "").strip()
    return v if v in const.SERVING_TIERS else ""


def gang_chips_from_annotation(pod: Pod) -> list[int]:
    """Member chip indices of a GRANTED gang (``ENV_GANG_CHIPS``), [] when
    absent/garbled — same tolerance as ``core_ids_from_annotation``."""
    v = annotations(pod).get(const.ENV_GANG_CHIPS)
    if not v:
        return []
    out: list[int] = []
    for part in str(v).split(","):
        try:
            out.append(int(part))
        except ValueError:
            return []
    return sorted(out)


def gang_per_chip_units(pod: Pod) -> int:
    """HBM units this gang claims on EACH member chip. Derived from the
    IMMUTABLE spec (total limits / member count) whenever it divides —
    the same tamper-resistance rule the single-chip audit gets from
    counting ``mem_units_of_pod``: an edited ``ENV_GANG_PER_CHIP``
    annotation must not shrink what every accounting layer books. The
    persisted annotation is only the fallback for annotation sets whose
    spec-derivation is impossible. 0 when underivable."""
    chips = gang_chips_from_annotation(pod)
    total = mem_units_of_pod(pod)
    if chips and total > 0 and total % len(chips) == 0:
        return total // len(chips)
    v = annotations(pod).get(const.ENV_GANG_PER_CHIP)
    if v is not None:
        try:
            per = int(v)
            return per if per > 0 else 0
        except ValueError:
            return 0
    return 0


def gang_usage_by_chip(pod: Pod) -> dict[int, int]:
    """Per-chip HBM units one granted gang pod holds ({} when the pod is
    not an annotated gang). One helper so the allocator overlay, the
    extender index, the reconciler audit, and the inspect CLI can never
    disagree about what a gang holds."""
    chips = gang_chips_from_annotation(pod)
    if not chips:
        return {}
    per = gang_per_chip_units(pod)
    if per <= 0:
        return {}
    return {idx: per for idx in chips}


def workload_class(pod: Pod) -> str:
    """The pod's declared QoS class (``ANN_WORKLOAD_CLASS``), normalized.

    Absent or garbled values read as ``latency-critical`` — the safe
    default is to protect a tenant, never to throttle one that forgot to
    label itself. One helper so admission, the informer indexes, the
    interference detector, and the inspect CLI can never disagree about
    a pod's class."""
    v = str(annotations(pod).get(const.ANN_WORKLOAD_CLASS, "") or "").strip()
    if v in const.WORKLOAD_CLASSES:
        return v
    return const.WORKLOAD_LATENCY_CRITICAL


def is_best_effort(pod: Pod) -> bool:
    return workload_class(pod) == const.WORKLOAD_BEST_EFFORT


def lora_adapter(pod: Pod) -> str:
    """The pod's requested LoRA adapter id (``ANN_LORA_ADAPTER``),
    stripped; empty string means the base model. One helper so the
    decision PATCH, the env injection, and the inspect CLI can never
    disagree about which adapter a pod asked for."""
    return str(annotations(pod).get(const.ANN_LORA_ADAPTER, "") or "").strip()


def assume_time_from_annotation(pod: Pod) -> int:
    v = annotations(pod).get(const.ENV_ASSUME_TIME)
    try:
        return int(v) if v is not None else 0
    except ValueError:
        return 0


# --- aggregate views -------------------------------------------------------


def candidate_pods(
    pods: Iterable[Pod], this_node: str, resource: str = const.RESOURCE_MEM
) -> list[Pod]:
    """Pending pods on this node requesting ``resource``, awaiting
    Allocate, oldest first.

    Reference: ``getCandidatePods`` (``podmanager.go:247-269``) — tpushare
    pods that are not yet (assumed AND assigned); pods scheduled to other
    nodes are skipped; duplicates (by UID) dropped.
    """
    seen: set[str] = set()
    out: list[Pod] = []
    for pod in pods:
        # Unscheduled pods (empty nodeName) are never candidates: Allocate
        # runs only after kubelet admitted the pod to *this* node
        # (reference warns+skips on mismatch, podmanager.go:200-205).
        if node_name(pod) != this_node:
            continue
        if uid(pod) in seen:
            continue
        seen.add(uid(pod))
        if mem_units_of_pod(pod, resource) <= 0:
            continue
        if is_assumed(pod) and is_assigned(pod):
            continue
        out.append(pod)
    out.sort(key=sort_key_by_creation)
    return out


def is_active(pod: Pod) -> bool:
    """Not terminally finished (Succeeded/Failed pods free their resources)."""
    return phase(pod) not in ("Succeeded", "Failed")


def used_units_by_chip(pods: Iterable[Pod]) -> dict[int, int]:
    """Annotation-declared HBM reservations of assigned labeled pods per
    chip index.

    Reference: ``getPodUsedGPUMemory`` (``podmanager.go:102-115``) counts
    only phase=Running pods; we deliberately count every *assigned*,
    non-terminal pod instead — a pod that Allocate() has placed holds its
    reservation while it is still Pending (image pull), and Running-only
    accounting would double-book the chip in that window.
    """
    used: dict[int, int] = {}
    for pod in pods:
        if not is_active(pod):
            continue
        if labels(pod).get(const.LABEL_RESOURCE_KEY) != const.LABEL_RESOURCE_VALUE:
            continue
        if not is_assigned(pod):
            continue
        gang = gang_usage_by_chip(pod)
        if gang:
            # multi-chip gang: the pod's total spreads per-chip over its
            # member chips (it deliberately carries no single IDX)
            for idx, per in gang.items():
                used[idx] = used.get(idx, 0) + per
            continue
        idx = chip_idx_from_annotation(pod)
        if idx < 0:
            continue
        used[idx] = used.get(idx, 0) + mem_units_of_pod(pod)
    return used


def core_hold_chips(pod: Pod) -> list[int]:
    """Chips one core pod holds. Primary source is the ``ENV_CORE_IDS``
    annotation the core allocator persists (kubelet may grant
    non-contiguous chips); legacy fallback is a contiguous range from the
    mem IDX annotation. One helper so the allocator ledger and the inspect
    CLI can never disagree about what a pod holds."""
    n = core_chips_of_pod(pod)
    if n <= 0:
        return []
    ids = core_ids_from_annotation(pod)
    if ids:
        return sorted(ids)
    idx = chip_idx_from_annotation(pod)
    if idx >= 0:
        return list(range(idx, idx + n))
    return []


def used_chips(pods: Iterable[Pod]) -> set[int]:
    """Chip indices exclusively held by assigned, non-terminal tpu-core
    pods (assigned-but-Pending holds count — see ``used_units_by_chip``)."""
    out: set[int] = set()
    for pod in pods:
        if not is_active(pod):
            continue
        if not is_assigned(pod):
            continue
        out.update(core_hold_chips(pod))
    return out
