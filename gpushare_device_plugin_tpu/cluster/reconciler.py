"""Periodic drift reconciler: annotations vs. ledger vs. checkpoint vs. kubelet.

Hot state now lives in four places — apiserver pod annotations (the
database), the AssumeCache reservation ledger (in-flight protection), the
allocation checkpoint (crash-surviving WAL), and kubelet's own notion of
which device IDs it granted. They are kept coherent by construction on the
happy path; this reconciler is the backstop for every path that isn't:
pods deleted mid-allocation, PATCHes that landed after their daemon died,
reservations whose owner hung, duplicate daemons racing a rollout.

One pass (``reconcile_once``):

1. **fence check** — verify this daemon's generation still owns the node
   annotation; a superseded instance latches fenced (allocation writes
   refuse) and skips repairs (the newer instance owns them).
2. **TTL expiry** — reap ledger entries older than the AssumeCache TTL
   (a crashed or hung PATCH can never permanently strand capacity).
3. **checkpoint resolution** — every replayed journal entry is resolved
   against the apiserver: pod assigned -> retro-commit (the crashed PATCH
   won); pod gone or unassigned -> retro-abort (nothing persisted).
   Either way its ledger reservation is released. Entries whose pod key
   is currently *claimed* belong to a live admission and are skipped.
   ``"move"`` entries (live defragmentation, ``allocator/defrag.py``)
   resolve by protocol phase instead: roll forward past ``switch``
   (re-issue the PATCH, restore the drained engine snapshot on the
   destination), roll back before it.
4. **ledger orphans** — unclaimed reservations whose pod is authoritatively
   gone (deleted mid-allocation) or already counted by annotations
   (redundant) are released.
5. **annotation audit** — assigned pods with garbled chip annotations and
   per-chip overcommit (annotations promising more than inventory) are
   counted as drift; they are observable, not auto-mutated — annotations
   are the database, and a reconciler that "fixes" the database on a
   hunch is how real outages start.
6. **kubelet diff** — when a grants feed is available (tests; the
   podresources API in production), pods assigned in annotations but
   unknown to kubelet — and vice versa — are counted as drift.

Everything emits ``tpushare_reconcile_drift_total{kind=...}`` /
``tpushare_reconcile_repairs_total{kind=...}`` so an operator can alert on
a node that keeps needing repair.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from .. import const
from ..allocator.assume import AssumeCache, PodKey
from ..allocator.checkpoint import AllocationCheckpoint
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY
from . import pods as P
from ..utils.metric_catalog import (
    RECONCILE_DRIFT_TOTAL as DRIFT_METRIC,
    RECONCILE_REPAIRS_TOTAL as REPAIR_METRIC,
    RECONCILE_RUNS_TOTAL as RUNS_METRIC,
    RECONCILE_SECONDS as DURATION_METRIC,
)

log = get_logger("cluster.reconciler")

DRIFT_HELP = (
    "State divergences observed between annotations, the reservation "
    "ledger, the checkpoint, and kubelet grants, by kind"
)
REPAIR_HELP = "Divergences repaired (released/resolved), by kind"
RUNS_HELP = "Reconcile passes by outcome"
DURATION_HELP = "Wall time of one reconcile pass"

DEFAULT_INTERVAL_S = 30.0


class DriftReconciler:
    def __init__(
        self,
        api: Any,
        pod_source: Any,
        assume: AssumeCache,
        checkpoint: AllocationCheckpoint | None = None,
        node_name: str = "",
        inventory: Any = None,
        kubelet_grants_fn: Callable[[], dict[PodKey, list[str]]] | None = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        on_fenced: Callable[[], None] | None = None,
        move_restore_fn: Callable[[PodKey, dict | None], None] | None = None,
        handoff_deliver_fn: Callable[[str, dict], str] | None = None,
        handoff_abort_fn: Callable[[str], Any] | None = None,
        scale_deliver_fn: Callable[[str, dict], Any] | None = None,
        scale_requeue_fn: Callable[[str, dict], Any] | None = None,
    ) -> None:
        """``kubelet_grants_fn() -> dict[PodKey, list[str]]`` supplies
        kubelet's granted device IDs per pod when a feed exists (the fake
        kubelet in tests; the podresources socket in production); None
        skips that diff. ``on_fenced()`` fires once when this instance
        discovers it was superseded. ``move_restore_fn(pod_key, snapshot)``
        re-admits a drained engine snapshot on the destination slice when
        a defragmentation move is rolled forward (allocator/defrag.py).
        ``handoff_deliver_fn(handoff_id, record)`` /
        ``handoff_abort_fn(handoff_id)`` are the decode tier's idempotent
        delivery sink and staging release for journaled KV handoffs found
        mid-protocol (serving/handoffproto.py); without a deliver hook a
        handoff entry stays pending — protective, never resolved blind.
        ``scale_deliver_fn(scale_id, record)`` /
        ``scale_requeue_fn(scale_id, record)`` are the fleet binding's
        survivor-restore and un-cordon/re-queue hooks for journaled
        scale-downs found mid-protocol (serving/router.py); same
        protective default without a deliver hook."""
        self._api = api
        self._pods = pod_source
        self._assume = assume
        self._ckpt = checkpoint
        self._node = node_name
        self._inv = inventory
        self._grants_fn = kubelet_grants_fn
        self._interval = interval_s
        self._on_fenced = on_fenced
        self._move_restore = move_restore_fn
        self._handoff_deliver = handoff_deliver_fn
        self._handoff_abort = handoff_abort_fn
        self._scale_deliver = scale_deliver_fn
        self._scale_requeue = scale_requeue_fn
        self._fenced_notified = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "DriftReconciler":
        self._thread = threading.Thread(
            target=self._run, name="drift-reconciler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        # first pass immediately: the post-restart replay set should be
        # resolved as soon as the control plane answers, not interval_s later
        while True:
            try:
                self.reconcile_once()
            except Exception as e:  # noqa: BLE001 — never kill the loop
                log.warning("reconcile pass failed: %s", e)
                REGISTRY.counter_inc(RUNS_METRIC, RUNS_HELP, outcome="error")
            if self._stop.wait(self._interval):
                return

    # --- the pass ---------------------------------------------------------

    def reconcile_once(self) -> dict[str, int]:
        """One full pass; -> counts by drift kind (for tests/logging)."""
        t0 = time.perf_counter()
        counts: dict[str, int] = {}

        def drift(kind: str, repaired: bool = False, n: int = 1) -> None:
            counts[kind] = counts.get(kind, 0) + n
            REGISTRY.counter_inc(DRIFT_METRIC, DRIFT_HELP, value=n, kind=kind)
            if repaired:
                REGISTRY.counter_inc(REPAIR_METRIC, REPAIR_HELP, value=n, kind=kind)

        # 1. fencing: a superseded daemon must stop writing AND stop
        # repairing — the newer instance owns both.
        if not self._check_fence(drift):
            REGISTRY.counter_inc(RUNS_METRIC, RUNS_HELP, outcome="fenced")
            return counts

        # 2. TTL expiry (the ledger's own lazy reaping, forced eagerly here
        # so a quiet node still unstrands within one reconcile interval)
        for key in self._assume.expire_stale():
            log.warning("reconcile: expired stale ledger entry for %s/%s", *key)
            drift("expired_reservation", repaired=True)

        # one refresh so "absent from the source" below means absent from
        # the apiserver now, not absent from a stale cache
        authoritative = True
        try:
            self._pods.refresh()
        except Exception as e:  # noqa: BLE001 — outage: observe, don't repair
            log.v(4, "reconcile: refresh failed (%s); repairs deferred", e)
            authoritative = False

        # 3. checkpoint resolution
        if self._ckpt is not None:
            self._resolve_checkpoint(drift)

        # 4. ledger orphans
        if authoritative:
            self._release_orphan_reservations(drift)

        # 5. annotation audit (observability only)
        self._audit_annotations(drift)

        # 6. kubelet grants diff
        if self._grants_fn is not None:
            self._diff_kubelet_grants(drift)

        REGISTRY.counter_inc(RUNS_METRIC, RUNS_HELP, outcome="ok")
        REGISTRY.observe(
            DURATION_METRIC, time.perf_counter() - t0, DURATION_HELP
        )
        if counts:
            log.info("reconcile pass repaired/observed drift: %s", counts)
        return counts

    # --- steps ------------------------------------------------------------

    def _check_fence(self, drift) -> bool:
        if self._ckpt is None or self._api is None or not self._node:
            return True
        try:
            ok = self._ckpt.verify_fence(self._api, self._node)
        except Exception as e:  # noqa: BLE001 — can't read the node: assume ok
            log.v(4, "reconcile: fence verify failed (%s); assuming owned", e)
            return True
        if not ok:
            drift("fenced")
            if not self._fenced_notified:
                self._fenced_notified = True
                if self._on_fenced is not None:
                    try:
                        self._on_fenced()
                    except Exception as e:  # noqa: BLE001 — notify hook
                        # a dead hook must not stop fencing, but eating it
                        # silently hid real wiring bugs (found by tpulint's
                        # hygiene rule; docs/analysis.md defects table)
                        log.warning("fenced-notification hook failed: %s", e)
        return ok

    def _fetch_pod(self, key: PodKey) -> tuple[dict | None, bool]:
        """-> (pod or None, authoritative). The apiserver GET is the truth;
        a cached source read is good enough only for presence, never for
        a deletion verdict."""
        if self._api is not None:
            from .apiserver import ApiError

            try:
                return self._api.get_pod(*key), True
            except ApiError as e:
                if e.status == 404:
                    return None, True
                return None, False
            except Exception:  # noqa: BLE001 — outage
                return None, False
        get_pod = getattr(self._pods, "get_pod", None)
        if get_pod is not None:
            return get_pod(*key), False
        return None, False

    def _resolve_checkpoint(self, drift) -> None:
        for key, data in self._ckpt.pending().items():
            if self._assume.is_claimed(key):
                continue  # a live admission owns this entry
            if data.get("kind") == "move":
                # a defragmentation move found mid-protocol: resolved by
                # phase — roll forward past "switch" (re-issue the PATCH,
                # restore the drained snapshot on the destination), roll
                # back before it (allocator/defrag.py owns the rules)
                if self._api is None:
                    continue  # no authoritative read: stay protective
                from ..allocator import defrag

                outcome = defrag.resolve_move(
                    self._ckpt, self._assume, self._api, key, data,
                    restore_fn=self._move_restore,
                )
                if outcome is not None:
                    drift(f"move_{outcome}", repaired=True)
                continue
            if data.get("kind") == "handoff":
                # a prefill->decode KV handoff found mid-protocol:
                # resolved by phase — roll forward (re-deliver,
                # idempotent by handoff id) at or past "import", roll
                # back to a local re-prefill before it. BOTH directions
                # end in a delivery through the decode tier's sink, so
                # the request is served exactly once whatever step the
                # crash hit (serving/handoffproto.py owns the rules).
                if self._handoff_deliver is None:
                    continue  # no decode tier wired: stay protective
                from ..serving import handoffproto

                outcome = handoffproto.resolve_handoff(
                    self._ckpt, self._assume, key, data,
                    deliver_fn=self._handoff_deliver,
                    abort_fn=self._handoff_abort,
                )
                if outcome is not None:
                    drift(f"handoff_{outcome}", repaired=True)
                continue
            if data.get("kind") == "scale":
                # a fleet scale-down found mid-protocol: resolved by
                # phase — roll forward (re-deliver the journaled drain
                # snapshot to a survivor, idempotent by snapshot_id) at
                # or past "migrate", roll back (un-cordon the replica
                # or re-queue the journaled rows on survivors) before
                # it. BOTH directions end with every in-flight request
                # scheduled exactly once (serving/router.py owns the
                # rules).
                if self._scale_deliver is None:
                    continue  # no fleet wired: stay protective
                from ..serving import router as fleet_router

                outcome = fleet_router.resolve_scale(
                    self._ckpt, self._assume, key, data,
                    deliver_fn=self._scale_deliver,
                    requeue_fn=self._scale_requeue,
                )
                if outcome is not None:
                    drift(f"scale_{outcome}", repaired=True)
                continue
            pod, authoritative = self._fetch_pod(key)
            if not authoritative:
                continue  # resolve next pass, reservation stays protective
            # The claim check above predates the slow GET: a kubelet retry
            # may have claimed the key and journaled a NEW begin since.
            # Resolution is therefore conditional on both the entry's seq
            # (only the incarnation we inspected resolves) and the claim
            # state at release time (a live worker keeps its reservation).
            seq = data.get("_seq")
            if pod is not None and P.is_assigned(pod):
                # the crashed PATCH won: the annotation is the record now
                if self._ckpt.commit(key, seq=seq):
                    self._assume.release_if_unclaimed(key)
                    log.info(
                        "reconcile: journal entry for %s/%s committed "
                        "(PATCH had landed before the crash)", *key
                    )
                    drift("replayed_commit", repaired=True)
            else:
                # pod gone, or still pending unassigned: nothing persisted
                if self._ckpt.abort(key, seq=seq):
                    self._assume.release_if_unclaimed(key)
                    log.info(
                        "reconcile: journal entry for %s/%s aborted "
                        "(no assignment persisted)", *key
                    )
                    drift("replayed_abort", repaired=True)

    def _release_orphan_reservations(self, drift) -> None:
        claims, mem, core = self._assume.snapshot()
        # Gang reservations are one atomic entry per pod: releasing an
        # orphaned gang frees EVERY member chip in this same pass — the
        # ledger cannot represent (and this loop cannot create) a
        # single-chip sliver of a partially-released gang.
        gang = self._assume.gang_snapshot()
        for key in list(mem) + list(core) + list(gang):
            if key in claims:
                continue  # live admission mid-PATCH: not drift
            if self._ckpt is not None and key in self._ckpt.pending():
                continue  # checkpoint resolution owns this one
            pod, authoritative = self._fetch_pod(key)
            if not authoritative:
                continue
            # release_if_unclaimed: the claim state is re-checked under
            # the ledger lock — a worker that claimed during the GET
            # keeps its reservation (see _resolve_checkpoint).
            if pod is None:
                if self._assume.release_if_unclaimed(key):
                    log.warning(
                        "reconcile: released reservation for deleted pod "
                        "%s/%s", *key,
                    )
                    drift("orphan_reservation", repaired=True)
            elif P.is_assigned(pod):
                # annotations count the pod; the reservation is redundant
                if self._assume.release_if_unclaimed(key):
                    drift("redundant_reservation", repaired=True)

    def _audit_annotations(self, drift) -> None:
        try:
            labeled = self._pods.labeled_pods()
        except Exception:  # noqa: BLE001
            return
        units_by_index = (
            self._inv.units_by_index() if self._inv is not None else None
        )
        used: dict[int, int] = {}
        for pod in labeled:
            if not P.is_active(pod) or not P.is_assigned(pod):
                continue
            if P.core_chips_of_pod(pod) > 0:
                if not P.core_hold_chips(pod):
                    drift("garbled_annotation")
                continue
            # Key on the GRANT annotation only (matching
            # gang_usage_by_chip): a pod that merely REQUESTS a gang
            # shape but was admitted single-chip (pre-gang daemon, or a
            # fallback path) is accounted by its IDX like every layer
            # accounts it — classing it garbled would drop its real
            # units from the overcommit sums.
            if const.ENV_GANG_CHIPS in P.annotations(pod):
                gang = P.gang_usage_by_chip(pod)
                if not gang:
                    # assigned gang with no usable member set / per-chip
                    # share: the grant is unaccountable
                    drift("garbled_annotation")
                    continue
                bad = [
                    i for i in gang
                    if units_by_index is not None and i not in units_by_index
                ]
                if bad:
                    drift("unknown_chip", n=len(bad))
                for i, per in gang.items():
                    if i in bad:
                        continue  # already reported; counting an off-
                        # inventory chip would re-fire as overcommit too
                    used[i] = used.get(i, 0) + per
                continue
            idx = P.chip_idx_from_annotation(pod)
            if idx < 0:
                drift("garbled_annotation")
                continue
            if units_by_index is not None and idx not in units_by_index:
                drift("unknown_chip")
                continue
            used[idx] = used.get(idx, 0) + P.mem_units_of_pod(pod)
        if units_by_index is not None:
            for idx, n in used.items():
                if n > units_by_index.get(idx, 0):
                    log.error(
                        "reconcile: chip %d overcommitted by annotations "
                        "(%d > %d units)", idx, n, units_by_index.get(idx, 0),
                    )
                    drift("overcommit")

    def _diff_kubelet_grants(self, drift) -> None:
        try:
            grants = self._grants_fn() or {}
        except Exception as e:  # noqa: BLE001
            log.v(4, "reconcile: kubelet grants read failed: %s", e)
            return
        try:
            labeled = self._pods.labeled_pods()
        except Exception:  # noqa: BLE001
            return
        assigned = {
            (P.namespace(p), P.name(p))
            for p in labeled
            if P.is_active(p) and P.is_assigned(p)
        }
        grant_keys = {tuple(k) for k in grants}
        for key in sorted(assigned - grant_keys):
            log.v(
                4, "reconcile: pod %s/%s assigned in annotations but "
                "unknown to kubelet", *key,
            )
            drift("kubelet_unknown")
        for key in sorted(grant_keys - assigned):
            log.v(
                4, "reconcile: kubelet granted devices to %s/%s which has "
                "no assignment annotation", *key,
            )
            drift("kubelet_orphan")
