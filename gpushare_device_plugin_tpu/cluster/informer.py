"""Watch-backed local pod cache — the client-go informer pattern, TPU-side.

The reference's Allocate() hot path LISTs the apiserver (or kubelet) on
every admission (``podmanager.go:141-190``): two HTTP round-trips per pod
(pending candidates + usage accounting) before the PATCH. This informer
replaces those reads with an in-memory cache maintained by a single
list+watch stream — the idiomatic Kubernetes controller design the
reference skipped — cutting Allocate() latency to roughly the cost of the
one unavoidable PATCH.

Consistency notes:
- The cache is eventually consistent. A pending pod that was *just* bound
  to this node may not have arrived on the watch when kubelet calls
  Allocate; ``refresh()`` (called by the allocator on a match miss) does a
  synchronous LIST to close that window, so the failure semantics are
  never worse than the reference's always-LIST behavior.
- After the allocator PATCHes annotations it feeds the response back via
  ``note_pod_update()`` so the next Allocate cannot re-match a pod whose
  MODIFIED event is still in flight.
- Restart safety is unchanged: the apiserver remains the only database
  (SURVEY.md section 5, checkpoint/resume); the cache is pure derivation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable

import requests

from .. import const
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY
from ..utils.retry import Backoff
from . import pods as P
from .apiserver import ApiError, ApiServerClient
from ..utils.lockrank import make_lock
from ..utils.metric_catalog import (
    INFORMER_APPLY_BATCH_EVENTS as APPLY_BATCH,
    INFORMER_INDEX_REBUILDS_TOTAL as INDEX_REBUILDS,
    INFORMER_STALENESS_SECONDS as STALENESS_GAUGE,
)

log = get_logger("cluster.informer")

# Relist failures back off with full jitter (a fixed 1 s loop turns an
# apiserver outage into a fleet-synchronized relist storm at recovery).
RELIST_BACKOFF_BASE_S = 0.5
RELIST_BACKOFF_MAX_S = 5.0
REFRESH_RETRIES = 3
REFRESH_DELAY_S = 0.25
# refresh() runs inside the Allocate admission path: a per-attempt HTTP
# timeout plus an overall deadline bound its total cost, so a dead
# apiserver yields a fast admission error instead of a kubelet worker
# stalled on the client's default connect timeout.
REFRESH_ATTEMPT_TIMEOUT_S = 1.0
REFRESH_DEADLINE_S = 3.0

STALENESS_HELP = (
    "Seconds since the cache last heard from the apiserver (LIST or "
    "watch event); rises during an outage while reads serve last-good data"
)
# Tombstone rv recorded when the evicted pod had no parseable
# resourceVersion: blocks every store for the key until an authoritative
# LIST shows it again (_merge_list clears sentinels on presence).
TOMB_SENTINEL = 1 << 62
# Tombstones normally die on relist GC, but a long watch-stable period
# never relists — the map must also be bounded by size and age so a 404
# storm (mass pod deletion mid-allocate) cannot grow it forever. Age
# chosen >> any realistic watch-event lag; by then the lagging event the
# tombstone guards against has either arrived or never will.
TOMBSTONE_MAX = 1024
TOMBSTONE_MAX_AGE_S = 600.0
TOMBSTONE_SWEEP_EVERY_S = 60.0

INDEX_REBUILDS_HELP = (
    "Full index rebuilds (registration + post-relist revalidation); "
    "everything else is incremental on_change maintenance"
)

APPLY_BATCH_HELP = (
    "Watch events applied per cache-lock acquisition (one transport read "
    "= one batch; a PATCH burst coalesces instead of paying N lock "
    "round-trips)"
)
APPLY_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def _is_read_timeout(e: Exception) -> bool:
    """True for an idle-watch read timeout however requests surfaces it.

    During streaming reads, requests wraps urllib3's ReadTimeoutError in a
    ConnectionError (NOT a requests Timeout subclass), so both the wrapper
    and the cause chain must be checked.
    """
    import urllib3.exceptions

    if isinstance(e, requests.exceptions.Timeout):
        return True
    seen: Exception | None = e
    for _ in range(5):
        if seen is None:
            return False
        if isinstance(seen, urllib3.exceptions.ReadTimeoutError):
            return True
        args = getattr(seen, "args", ())
        seen = next(
            (a for a in args if isinstance(a, Exception)),
            getattr(seen, "__cause__", None),
        )
    return False


def _parse_rv(rv) -> int | None:
    return int(rv) if isinstance(rv, str) and rv.isdigit() else None


def _rv_int(pod: dict) -> int | None:
    return _parse_rv(pod.get("metadata", {}).get("resourceVersion", ""))


class PodInformer:
    """List+watch cache of this node's pods, implementing the PodSource
    protocol (``pending_pods``/``running_share_pods``) plus the informer
    extras (``refresh``/``note_pod_update``)."""

    def __init__(self, client: ApiServerClient, node_name: str = "") -> None:
        """``node_name`` scopes the cache to one node's pods (the daemon's
        use); empty means cluster-wide (the scheduler extender's use —
        placement accounting needs every node's pods, including assumed
        pods that carry annotations but no label yet)."""
        from .indexes import LabeledPodIndex, PendingPodIndex, WorkloadClassIndex
        from .usage import NodeChipUsage

        self._c = client
        self._node = node_name
        self._field_selector = f"spec.nodeName={node_name}" if node_name else ""
        self._cache: dict[tuple[str, str], dict] = {}
        # key -> (rv at eviction, monotonic stamp): blocks lagging in-flight
        # watch events from resurrecting a pod the apiserver reported gone
        # (PATCH 404); the stamp drives the age/size sweep
        self._tombstones: dict[tuple[str, str], tuple[int, float]] = {}
        self._last_tomb_sweep = time.monotonic()
        self._lock = make_lock("informer.cache")
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._live_response = None  # in-flight watch, closed by stop()
        # Incremental aggregates maintained on every cache mutation so hot
        # paths read O(chips)/O(nodes) instead of rescanning the cache.
        # NodeChipUsage is node-scoped only: a cluster-wide cache would
        # merge chip 0 of every node into one bucket (consumers there
        # register their own per-node index via add_index). The pod-set
        # indexes (pending-by-resource, labeled-by-value) apply to both
        # scopes and make pending_pods()/labeled_pods() O(answer) instead
        # of O(cache).
        self._usage = NodeChipUsage() if node_name else None
        self._pending = PendingPodIndex()
        self._labeled = LabeledPodIndex()
        self._classes = WorkloadClassIndex()
        self._indexes: list = [self._pending, self._labeled, self._classes]
        if self._usage:
            self._indexes.append(self._usage)
        # monotonic timestamp of the last successful apiserver contact;
        # drives the staleness gauge while the cache serves degraded reads
        self._last_sync = time.monotonic()
        self._scope = node_name or "cluster"

    # --- lifecycle --------------------------------------------------------

    def start(self, sync_timeout_s: float = 10.0) -> "PodInformer":
        self._thread = threading.Thread(
            target=self._run, name="pod-informer", daemon=True
        )
        self._thread.start()
        if not self._synced.wait(sync_timeout_s):
            log.warning(
                "informer did not sync within %.1fs; reads fall back to "
                "refresh-on-miss until the first LIST lands", sync_timeout_s
            )
        return self

    def stop(self) -> None:
        import socket as _socket
        import time as _time

        self._stop.set()
        # The watch thread may be anywhere between issuing the GET and
        # blocking in recv; poll briefly until the live response appears,
        # then shutdown() its socket — close() alone cannot interrupt a
        # blocked recv, it would wait out the whole read timeout.
        deadline = _time.monotonic() + 2.0
        while self._thread is not None and self._thread.is_alive():
            resp = self._live_response
            if resp is not None:
                try:
                    sock = resp.raw.connection.sock
                    if sock is not None:
                        sock.shutdown(_socket.SHUT_RDWR)
                except (OSError, AttributeError):  # already closed/racing
                    pass
                try:
                    resp.close()
                except OSError:  # already closed
                    pass
                break
            if _time.monotonic() > deadline:
                break
            _time.sleep(0.01)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def staleness_s(self) -> float:
        """Age of the newest apiserver data in the cache. Near zero while
        the watch is live; rises monotonically through an outage."""
        return time.monotonic() - self._last_sync

    def _mark_synced(self) -> None:
        self._last_sync = time.monotonic()
        REGISTRY.gauge_set(
            STALENESS_GAUGE, 0.0, STALENESS_HELP, scope=self._scope
        )

    def _mark_stale(self) -> None:
        REGISTRY.gauge_set(
            STALENESS_GAUGE, self.staleness_s(), STALENESS_HELP,
            scope=self._scope,
        )

    @property
    def synced(self) -> bool:
        """True once an authoritative LIST has seeded the cache. Consumers
        that cannot tolerate a cold cache (the extender would place pods
        onto chips it believes empty) must fall back to direct LISTs, or
        call ``refresh()``, while this is False."""
        return self._synced.is_set()

    # --- incremental indexes ----------------------------------------------

    def add_index(self, index: Any) -> "PodInformer":
        """Register an aggregate maintained on every cache mutation.

        ``index`` implements ``rebuild(pods)`` (called now, to fold in the
        current cache) and ``on_change(old, new)`` (called under the cache
        lock on every store/delete: ``old`` is the prior cached pod or
        None, ``new`` the replacement or None)."""
        with self._lock:
            self._indexes.append(index)
            index.rebuild(list(self._cache.values()))
        REGISTRY.counter_inc(
            INDEX_REBUILDS, INDEX_REBUILDS_HELP,
            scope=self._scope, reason="register",
        )
        return self

    def revalidate_indexes(self) -> None:
        """Escape hatch: rebuild every index from the cache in one atomic
        pass. Called after each relist — incremental maintenance is exact
        by construction, but a relist is the moment the cache itself was
        just re-anchored to an authoritative LIST, so re-deriving the
        aggregates there turns any would-be drift bug from a permanent
        corruption into a one-relist-cycle blip (and the rebuild counter
        makes the frequency observable)."""
        with self._lock:
            pods = list(self._cache.values())
            for ix in self._indexes:
                ix.rebuild(pods)
        REGISTRY.counter_inc(
            INDEX_REBUILDS, INDEX_REBUILDS_HELP,
            scope=self._scope, reason="revalidate",
        )

    def _cache_set(self, key: tuple[str, str], pod: dict) -> None:
        """Caller must hold self._lock."""
        old = self._cache.get(key)
        self._cache[key] = pod
        for ix in self._indexes:
            ix.on_change(old, pod)

    def _cache_pop(self, key: tuple[str, str]) -> dict | None:
        """Caller must hold self._lock."""
        old = self._cache.pop(key, None)
        if old is not None:
            for ix in self._indexes:
                ix.on_change(old, None)
        return old

    # --- list+watch loop --------------------------------------------------

    def _key(self, pod: dict) -> tuple[str, str]:
        return P.namespace(pod), P.name(pod)

    def _relist(self) -> str:
        items, rv = self._c.list_pods_with_rv(field_selector=self._field_selector)
        # rv-guarded merge, NOT a wholesale replace: a LIST served just
        # before a concurrent PATCH/evict landed must not revert the
        # note_pod_update/evict state (that would re-open the re-match
        # window on the Allocate path).
        self._merge_list(items, rv, gc_tombstones=True)
        self.revalidate_indexes()
        self._synced.set()
        self._mark_synced()
        log.v(4, "informer listed %d pods at rv=%s", len(items), rv)
        return rv

    def _merge_list(self, items: list[dict], rv: str, gc_tombstones: bool = False) -> None:
        """Fold an authoritative LIST into the cache: prune absences not
        provably newer than the LIST, keep newer cached entries.

        ``gc_tombstones`` drops tombstones older than the LIST rv — only
        valid from the watch thread itself (it re-watches from this rv, so
        no older event can arrive); refresh() callers race the live stream
        and must keep them.
        """
        list_rv = _parse_rv(rv)
        with self._lock:
            listed = {self._key(p) for p in items}
            for key in [k for k in self._cache if k not in listed]:
                cached_rv = _rv_int(self._cache[key])
                if list_rv is None or cached_rv is None or cached_rv <= list_rv:
                    self._cache_pop(key)
            for key, (tomb, _stamp) in list(self._tombstones.items()):
                if key in listed:
                    # Present in a LIST that provably postdates the
                    # eviction -> live now (a recreation). A LIST whose rv
                    # is unknown or older may have been served before the
                    # deletion landed; keeping the tombstone makes
                    # _store_if_newer drop that stale copy instead of
                    # resurrecting the ghost. Sentinel tombstones (no rv
                    # was parseable at evict time, stored as 1<<62) can
                    # never win an rv comparison — for them an
                    # authoritative LIST presence is the best evidence
                    # available and must clear the block, or the key would
                    # be uncacheable until restart.
                    if tomb >= TOMB_SENTINEL or (
                        list_rv is not None and list_rv >= tomb
                    ):
                        self._tombstones.pop(key)
                elif gc_tombstones and list_rv is not None and tomb <= list_rv:
                    self._tombstones.pop(key)
            for p in items:
                self._store_if_newer(self._key(p), p)

    def _store_if_newer(self, key: tuple[str, str], pod: dict) -> None:
        """Caller must hold self._lock. Drops updates whose resourceVersion
        is not newer than the cached entry's — an in-flight older watch
        event must not revert a pod fed in by note_pod_update()/refresh()
        (that would re-open the re-match window those hooks close)."""
        new_rv = _rv_int(pod)
        entry = self._tombstones.get(key)
        if entry is not None:
            # A lagging pre-deletion event must not resurrect an evicted
            # ghost; anything provably newer is a legitimate recreation.
            if new_rv is None or new_rv <= entry[0]:
                return
            self._tombstones.pop(key, None)
        cached = self._cache.get(key)
        if cached is not None:
            old_rv = _rv_int(cached)
            if old_rv is not None and new_rv is not None and new_rv <= old_rv:
                return
        self._cache_set(key, pod)

    def _apply_locked(self, etype: str, pod: dict) -> None:
        """One watch event against the cache. Caller must hold self._lock."""
        key = self._key(pod)
        if etype == "DELETED":
            # rv-guarded like stores: a lagging DELETED for an old
            # instance of the name must not evict a live recreation
            # that refresh() already cached at a higher rv.
            cached = self._cache.get(key)
            ev_rv, cached_rv = _rv_int(pod), (
                _rv_int(cached) if cached is not None else None
            )
            if (
                cached_rv is None
                or ev_rv is None
                or cached_rv <= ev_rv
            ):
                self._cache_pop(key)
            # the real deletion arrived; the tombstone has served its
            # purpose (a later recreation must not be blocked)
            entry = self._tombstones.get(key)
            if entry is not None and (ev_rv is None or ev_rv >= entry[0]):
                self._tombstones.pop(key)
        elif etype in ("ADDED", "MODIFIED"):
            self._store_if_newer(key, pod)
        # A pod moving OFF this node arrives as MODIFIED with a different
        # nodeName (field-selector watches emit it as DELETED on a real
        # apiserver; tolerate both shapes). Cluster-wide informers keep
        # every pod.
        if (
            self._node
            and etype != "DELETED"
            and P.node_name(pod) not in ("", self._node)
        ):
            self._cache_pop(key)

    def _apply(self, etype: str, pod: dict) -> None:
        self.apply_batch([(etype, pod)])

    def apply_batch(
        self, events: Iterable[tuple[str, dict]]
    ) -> tuple[str | None, dict | None]:
        """Apply a burst of watch events under ONE cache/index-lock
        acquisition — the watch thread hands every transport read here, so
        an N-event PATCH burst costs one lock round-trip, with the indexes
        maintained incrementally per event (no revalidate). Returns the
        last applied resourceVersion (None if none parsed) and the ERROR
        event's object when the stream signaled failure (events after it
        are dropped; the caller relists)."""
        rv: str | None = None
        error_obj: dict | None = None
        applied = 0
        with self._lock:
            for etype, pod in events:
                if etype == "ERROR":
                    error_obj = pod if isinstance(pod, dict) else {}
                    break
                self._apply_locked(etype, pod)
                applied += 1
                rv = pod.get("metadata", {}).get("resourceVersion", rv)
            now = time.monotonic()
            if (
                self._tombstones
                and now - self._last_tomb_sweep > TOMBSTONE_SWEEP_EVERY_S
            ):
                self._sweep_tombstones(now)
        if applied:
            REGISTRY.observe(
                APPLY_BATCH, float(applied), APPLY_BATCH_HELP,
                buckets=APPLY_BATCH_BUCKETS, scope=self._scope,
            )
        return rv, error_obj

    def _run(self) -> None:
        rv = "0"
        need_list = True
        backoff = Backoff(base_s=RELIST_BACKOFF_BASE_S, max_s=RELIST_BACKOFF_MAX_S)
        while not self._stop.is_set():
            try:
                if need_list:
                    rv = self._relist()
                    need_list = False
                    backoff.reset()
                batches = self._c.watch_pods_batched(
                    resource_version=rv,
                    field_selector=self._field_selector,
                    on_response=lambda r: setattr(self, "_live_response", r),
                )
                for batch in batches:
                    if self._stop.is_set():
                        return
                    backoff.reset()
                    self._mark_synced()
                    batch_rv, error_obj = self.apply_batch(batch)
                    if batch_rv is not None:
                        rv = batch_rv
                    if error_obj is not None:
                        # In-stream failure (a real apiserver reports an
                        # expired rv as HTTP 200 + one ERROR/Status event,
                        # code 410). Relist to re-seed.
                        log.v(
                            4, "watch ERROR event (code=%s); relisting",
                            error_obj.get("code"),
                        )
                        need_list = True
                        break
                # clean server close: re-watch from the last seen rv
            except ApiError as e:
                if e.status == 410:  # Gone: our rv fell out of history
                    log.v(4, "watch rv=%s gone; relisting", rv)
                else:
                    log.warning("watch failed (%s); relisting", e)
                need_list = True
                self._mark_stale()
                self._stop.wait(backoff.next())
            except Exception as e:  # noqa: BLE001 — timeouts, resets, closes
                if _is_read_timeout(e):
                    # Routine idle-watch read timeout: the cache is still
                    # good — re-watch from the last rv, no LIST, no backoff.
                    log.v(4, "idle watch timed out; re-watching from rv=%s", rv)
                else:
                    # Covers CircuitOpenError too: while the breaker is
                    # open each pass fails instantly, so the jittered
                    # backoff is what keeps this from being a hot loop.
                    log.v(4, "watch interrupted (%s); relisting", e)
                    need_list = True
                    self._mark_stale()
                    self._stop.wait(backoff.next())
            finally:
                self._live_response = None

    # --- PodSource protocol ----------------------------------------------

    def pending_pods(self) -> list[dict]:
        return self._pending.pods()

    def pending_share_pods(self, resource: str) -> list[dict]:
        """Pending pods requesting ``resource`` — the allocator's match
        universe, O(bucket) instead of O(cache) (the full-scan filter it
        replaces lives on in ``P.candidate_pods`` as the screen over this
        pre-filtered set)."""
        return self._pending.pods(resource)

    def running_share_pods(self) -> list[dict]:
        return self._labeled.pods(const.LABEL_RESOURCE_VALUE)

    def labeled_pods(self) -> list[dict]:
        """All pods bearing the tpu/resource label (mem or core) — one
        snapshot for cross-resource accounting on the Allocate path."""
        return self._labeled.pods()

    def share_pods_by_class(self, workload_class: str) -> list[dict]:
        """Active share pods of one declared workload class (normalized;
        ``cluster.indexes.WorkloadClassIndex``) — the interference
        plane's class lookup, O(answer)."""
        return self._classes.pods(workload_class)

    def chip_residency(self) -> dict[int, dict[str, str]]:
        """Per-chip resident share pods + workload classes (the
        interference detector's co-residency input), maintained
        incrementally; {} on a cluster-wide cache (residency is a
        node-scoped notion, like ``chip_state``)."""
        if self._usage is None:
            return {}
        return self._usage.residency()

    def all_pods(self) -> list[dict]:
        """Every cached pod (the extender's placement accounting reads
        annotated-but-unlabeled assumed pods too)."""
        with self._lock:
            return list(self._cache.values())

    def get_pod(self, namespace: str, name: str) -> dict | None:
        with self._lock:
            return self._cache.get((namespace, name))

    def chip_state(self) -> tuple[dict[int, int], set[int]]:
        """O(chips) usage read for the Allocate path: -> (mem units used
        per chip, exclusively-held chips), maintained incrementally instead
        of rescanning every labeled pod per admission. Falls back to a
        synchronous LIST when the cache has never synced (a cold cache
        reads as an empty node and would double-book every chip)."""
        if self._usage is None:
            raise RuntimeError(
                "chip_state() requires a node-scoped informer; a "
                "cluster-wide cache would merge chip indices across nodes"
            )
        if not self._synced.is_set():
            self.refresh()
        return self._usage.snapshot()

    # --- informer extras --------------------------------------------------

    def refresh(self) -> None:
        """Synchronous LIST — closes the just-scheduled-pod race on a match
        miss. Retried like the list-backed source's reads (the allocator
        calls this exactly when admission hangs on the answer, so it must
        not be weaker than the reference's always-LIST path). The watch
        keeps streaming independently.

        Deletions are reconciled too: a cached pod absent from the LIST
        whose resourceVersion predates the LIST's collection rv is gone on
        the server (its DELETED event is in flight or the watch is lagging)
        and must not stay matchable — a stale pending pod matched ahead of
        the real same-size pod turns into a 404 on PATCH and a terminal
        UnexpectedAdmissionError for the innocent pod."""
        from ..utils.retry import retry

        items, rv = retry(
            lambda: self._c.list_pods_with_rv(
                field_selector=self._field_selector,
                timeout_s=REFRESH_ATTEMPT_TIMEOUT_S,
            ),
            attempts=REFRESH_RETRIES,
            delay_s=REFRESH_DELAY_S,
            backoff=2.0,
            jitter=True,
            deadline_s=REFRESH_DEADLINE_S,
        )
        self._merge_list(items, rv)
        # an authoritative LIST seeds the cache as well as _relist does
        self._synced.set()
        self._mark_synced()

    def evict(self, pod: dict) -> None:
        """Drop a pod the apiserver reported gone (PATCH 404) so the next
        match cannot pick it again ahead of a live same-size pod. A
        tombstone at the evicted rv keeps lagging in-flight watch events
        from re-inserting the ghost behind our back."""
        key = self._key(pod)
        with self._lock:
            cached = self._cache_pop(key)
            rv = _rv_int(cached) if cached is not None else None
            if rv is None:
                rv = _rv_int(pod)
            now = time.monotonic()
            self._tombstones[key] = (
                rv if rv is not None else TOMB_SENTINEL, now
            )
            if len(self._tombstones) > TOMBSTONE_MAX:
                self._sweep_tombstones(now)

    def _sweep_tombstones(self, now: float) -> None:
        """Caller must hold self._lock. Age out expired tombstones; if the
        map still exceeds the size cap, drop oldest-first (a dropped
        tombstone only re-opens the brief lagging-event window the next
        relist would have closed anyway — an acceptable trade against an
        unbounded map)."""
        self._last_tomb_sweep = now
        for key, (_rv, stamp) in list(self._tombstones.items()):
            if now - stamp > TOMBSTONE_MAX_AGE_S:
                self._tombstones.pop(key)
        if len(self._tombstones) > TOMBSTONE_MAX:
            by_age = sorted(self._tombstones.items(), key=lambda kv: kv[1][1])
            for key, _entry in by_age[: len(self._tombstones) - TOMBSTONE_MAX]:
                self._tombstones.pop(key)

    def note_pod_update(self, pod: dict) -> None:
        """Feed a freshly-PATCHed pod straight into the cache so the next
        read sees it before its MODIFIED event arrives."""
        if pod:
            with self._lock:
                self._store_if_newer(self._key(pod), pod)
