"""Secondary pod-set indexes maintained on informer cache mutations.

Round 2 moved the Allocate path's reads into the watch cache, but every
``pending_pods()`` / ``labeled_pods()`` read still scanned the *entire*
cache — O(cache) pure-Python filtering per admission, which at fleet pod
counts dominates the in-memory half of the hot path. These indexes
subscribe to the informer's mutation stream (``PodInformer.add_index``)
and maintain the exact subsets the hot paths read, so each read is O(size
of the answer), not O(cache):

- ``PendingPodIndex``: pending pods, bucketed by which share resource they
  request (tpu-mem / tpu-core) — the allocator's match step reads only its
  own resource's bucket;
- ``LabeledPodIndex``: pods bearing the tpu/resource label, bucketed by
  label value — usage accounting and the running-share read.

The membership of a pod is a pure function of its JSON, so remove-old /
add-new on every mutation keeps each index exactly equal to the full-scan
filter at every point (tested property-style in
``tests/test_index_property.py``).
"""

from __future__ import annotations


from .. import const
from . import pods as P
from ..utils.lockrank import make_lock

_Key = tuple[str, str]


def _key(pod: dict) -> _Key:
    return P.namespace(pod), P.name(pod)


class _BucketedPodIndex:
    """Base: a keyed pod set partitioned into buckets by a pure function.

    Subclasses define ``_buckets_of(pod) -> tuple[str, ...]`` — the buckets
    a pod belongs to (empty tuple = not in the index at all).
    """

    def __init__(self):
        self._lock = make_lock("cluster.podindex")
        self._all: dict[_Key, dict] = {}
        self._buckets: dict[str, dict[_Key, dict]] = {}

    def _buckets_of(self, pod: dict) -> tuple[str, ...]:
        raise NotImplementedError

    # --- informer index protocol -----------------------------------------

    def rebuild(self, pods: list[dict]) -> None:
        with self._lock:
            self._all.clear()
            self._buckets.clear()
            for pod in pods:
                self._add(pod)

    def on_change(self, old: dict | None, new: dict | None) -> None:
        with self._lock:
            if old is not None:
                self._remove(old)
            if new is not None:
                self._add(new)

    # --- internals (lock held) -------------------------------------------

    def _add(self, pod: dict) -> None:
        buckets = self._buckets_of(pod)
        if not buckets:
            return
        key = _key(pod)
        self._all[key] = pod
        for b in buckets:
            self._buckets.setdefault(b, {})[key] = pod

    def _remove(self, pod: dict) -> None:
        key = _key(pod)
        if self._all.pop(key, None) is None:
            return
        for members in self._buckets.values():
            members.pop(key, None)

    # --- reads ------------------------------------------------------------

    def pods(self, bucket: str | None = None) -> list[dict]:
        """Members of ``bucket`` (all members when None). The list is a
        copy; the pod dicts are the live cache entries (read-only by
        convention, same as every informer read)."""
        with self._lock:
            if bucket is None:
                return list(self._all.values())
            return list(self._buckets.get(bucket, {}).values())


class PendingPodIndex(_BucketedPodIndex):
    """Pending pods, bucketed by requested share resource.

    ``pods()`` is the PodSource ``pending_pods()`` answer; ``pods(resource)``
    is the allocator's match universe for one resource — already pre-filtered
    so ``candidate_pods`` only sorts/screens actual candidates.
    """

    RESOURCES = (const.RESOURCE_MEM, const.RESOURCE_CORE)

    def _buckets_of(self, pod: dict) -> tuple[str, ...]:
        if P.phase(pod) != "Pending":
            return ()
        requested = tuple(
            r for r in self.RESOURCES if P.mem_units_of_pod(pod, resource=r) > 0
        )
        # pending pods requesting no share resource still belong to the
        # index (pending_pods() must return every pending pod) — they just
        # live in no resource bucket
        return requested or ("",)


class LabeledPodIndex(_BucketedPodIndex):
    """Pods bearing the tpu/resource label, bucketed by label value
    (tpu-mem / tpu-core) — the usage-accounting snapshot reads."""

    def _buckets_of(self, pod: dict) -> tuple[str, ...]:
        value = P.labels(pod).get(const.LABEL_RESOURCE_KEY)
        if value is None:
            return ()
        return (value,)


class WorkloadClassIndex(_BucketedPodIndex):
    """Active share pods bucketed by their declared workload class
    (``tpushare.aliyun.com/workload-class``, normalized — absent reads
    as latency-critical). The interference plane's class lookup: the
    detector and the inspect CLI ask "which best-effort pods are live on
    this node" without rescanning the cache."""

    def _buckets_of(self, pod: dict) -> tuple[str, ...]:
        if not P.is_active(pod):
            return ()
        if P.labels(pod).get(const.LABEL_RESOURCE_KEY) != const.LABEL_RESOURCE_VALUE:
            return ()
        return (P.workload_class(pod),)
