"""Co-tenant interference detection: correlating chip co-residency with
decode-step p99 inflation.

Two pods sharing one chip only partition HBM; compute contention between
them is invisible to every accounting layer this repo has — the units
add up, the SLOs just quietly die. The serving engines now measure their
own decode-step latency (``serving/profiler.py``); this module supplies
the *attribution*: which chip, which victim, which aggressor, how bad.

The algorithm is deliberately boring (boring is debuggable at 3am):

1. **Residency**: per chip, the set of resident share pods and their
   declared workload classes (``tpushare.aliyun.com/workload-class``,
   normalized by ``cluster.pods.workload_class``). Computed either from
   the maintained ``NodeChipUsage`` index (:meth:`NodeChipUsage.residency`)
   or the pure :func:`residency_from_pods` over any pod list.
2. **Solo baseline**: while a pod is the *only* resident on every chip
   it occupies, its rolling step p99 feeds an EWMA baseline — the
   "solo window". No co-tenant, no contention, so this is what the
   hardware owes the pod.
3. **Verdict**: while a latency-critical pod shares a chip, its current
   step p99 over its solo baseline is the **interference ratio**;
   every co-resident pod is exported as an aggressor:
   ``tpushare_interference_ratio{chip,victim,aggressor}``. Ratios at or
   above ``threshold`` are flagged in the
   ``tpushare.aliyun.com/interference`` node annotation the inspect CLI
   (and its ``top`` view) renders.

The detector never *acts* — the best-effort governor
(``serving/governor.py``) reacts to the SLO burn signal, and the
admission/relocation policy (ROADMAP item 1's second half) will consume
these verdicts in a later PR. Measurement and reaction stay separately
testable.

Lock discipline (``cluster.interference``, rank 63): inputs are gathered
BEFORE the detector lock is taken, gauges publish after it is dropped —
the lock covers only the baseline/report dictionaries.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Iterable, Mapping

from .. import const
from . import pods as P
from ..utils.lockrank import make_lock
from ..utils.log import get_logger
from ..utils.metrics import MetricsRegistry, REGISTRY
from ..utils.metric_catalog import (
    ENGINE_STEP_P99_SECONDS as STEP_P99_GAUGE,
    INTERFERENCE_RATIO as RATIO_GAUGE,
)

log = get_logger("cluster.interference")

RATIO_HELP = (
    "Victim decode-step p99 over its solo-window baseline while sharing "
    "its chip with the aggressor (1.0 = no inflation; 0 = pair no longer "
    "co-resident)"
)

# Step-p99 gauge the serving engines export (serving/profiler.py); the
# detector's default signal source reads it back off the registry.

# Passes a known pod may be absent from residency before its baseline is
# pruned: tolerates a brief informer flap without forgetting solo state,
# while bounding both memory under churn and how long a recreated
# same-name pod could inherit a dead pod's baseline (~3 intervals).
_PRUNE_AFTER_ABSENT = 3


def step_p99s_from_urls(
    urls: Iterable[str], timeout_s: float = 5.0
) -> dict[str, float]:
    """Scrape the engines' ``tpushare_engine_step_p99_seconds`` gauges
    from ``/metrics`` endpoints (the serving pods' ``--metrics-port``) —
    the daemon-side signal source when the engines do NOT share the
    daemon's process registry. Stdlib-only (the daemon must not grow a
    requests dependency); unreachable endpoints are skipped, partial
    telemetry beats none (same policy as the CLI's scrapers)."""
    import urllib.request

    out: dict[str, float] = {}
    for url in urls:
        full = url.rstrip("/")
        if not full.endswith("/metrics"):
            full += "/metrics"
        try:
            with urllib.request.urlopen(full, timeout=timeout_s) as resp:
                text = resp.read().decode("utf-8", "replace")
        except (OSError, ValueError) as e:
            log.v(4, "interference: scrape of %s failed (%s)", full, e)
            continue
        for line in text.splitlines():
            if not line.startswith(STEP_P99_GAUGE):
                continue
            try:
                metric, value = line.rsplit(None, 1)
                val = float(value)
            except ValueError:
                continue
            pod = ""
            if "{" in metric:
                _, raw = metric.split("{", 1)
                for part in raw.rstrip("}").split(","):
                    if "=" in part:
                        k, v = part.split("=", 1)
                        if k.strip() == "pod":
                            pod = v.strip().strip('"').replace('\\"', '"')
            if pod:
                out[pod] = val
    return out


def residency_from_pods(
    pods: Iterable[Mapping[str, Any]],
) -> dict[int, dict[str, str]]:
    """Per-chip residency: chip index -> {"ns/name": workload class} for
    every active, assigned share pod (gang pods reside on every member
    chip). The pure-function twin of :meth:`NodeChipUsage.residency`,
    for list-backed pod sources and tests."""
    out: dict[int, dict[str, str]] = {}
    for pod in pods:
        if not P.is_active(pod) or not P.is_assigned(pod):
            continue
        if P.labels(pod).get(const.LABEL_RESOURCE_KEY) != const.LABEL_RESOURCE_VALUE:
            continue
        gang = P.gang_usage_by_chip(pod)
        chips = list(gang) if gang else []
        if not chips:
            idx = P.chip_idx_from_annotation(pod)
            if idx < 0:
                continue
            chips = [idx]
        key = f"{P.namespace(pod)}/{P.name(pod)}"
        cls = P.workload_class(pod)
        for idx in chips:
            out.setdefault(idx, {})[key] = cls
    return out


@dataclasses.dataclass(frozen=True)
class InterferenceReport:
    """One victim's verdict on one chip for the current pass."""

    chip: int
    victim: str  # "ns/name"
    victim_class: str
    aggressors: tuple[str, ...]
    ratio: float  # current p99 / solo baseline p99
    victim_p99: float
    baseline_p99: float
    flagged: bool  # ratio >= detector threshold

    def to_dict(self) -> dict[str, Any]:
        return {
            "victim": self.victim,
            "victim_class": self.victim_class,
            "aggressors": list(self.aggressors),
            "ratio": round(self.ratio, 3),
            "victim_p99_s": round(self.victim_p99, 6),
            "baseline_p99_s": round(self.baseline_p99, 6),
            "flagged": self.flagged,
        }


class InterferenceDetector:
    """Correlates residency with step-p99 inflation against solo baselines.

    ``threshold`` flags a verdict (annotation + ``flagged``);
    ``baseline_alpha`` is the solo-window EWMA weight of the newest
    sample. Baselines persist across co-residency episodes — the whole
    point is remembering what solo looked like once a co-tenant lands.
    """

    def __init__(
        self,
        *,
        threshold: float = 1.25,
        baseline_alpha: float = 0.3,
        baseline_cooldown_passes: int = 2,
        registry: MetricsRegistry | None = None,
    ) -> None:
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        if not 0.0 < baseline_alpha <= 1.0:
            raise ValueError(
                f"baseline_alpha must be in (0, 1], got {baseline_alpha}"
            )
        if baseline_cooldown_passes < 1:
            raise ValueError(
                f"baseline_cooldown_passes must be >= 1, got "
                f"{baseline_cooldown_passes}"
            )
        self.threshold = threshold
        self._alpha = baseline_alpha
        self._cooldown = baseline_cooldown_passes
        self._reg = registry if registry is not None else REGISTRY
        self._lock = make_lock("cluster.interference")
        self._baseline: dict[str, float] = {}  # pod key -> solo p99 EWMA
        # consecutive passes each pod has been solo: the exported step
        # p99 is a ROLLING window that lags residency, so the first
        # solo passes after a co-residency episode still carry the
        # contended tail — absorbing them would inflate the baseline
        # and mask the next episode (upward updates wait out the
        # cooldown; a LOWER p99 is always safe to absorb immediately)
        self._solo_streak: dict[str, int] = {}
        # consecutive passes a known pod has been ABSENT from residency:
        # after _PRUNE_AFTER_ABSENT passes its baseline is dropped, so
        # the tables stay bounded under pod churn and a recreated pod
        # with the same ns/name (possibly a very different model) cannot
        # inherit a dead pod's baseline and fake a verdict
        self._absent: dict[str, int] = {}
        self._reports: list[InterferenceReport] = []
        self._exported: set[tuple[str, str, str]] = set()  # (chip, victim, aggressor)
        self._passes = 0

    # --- introspection ----------------------------------------------------

    def baseline(self, pod_key: str) -> float | None:
        with self._lock:
            return self._baseline.get(pod_key)

    def reports(self) -> list[InterferenceReport]:
        """The last pass's verdicts (CLI/annotation raw material)."""
        with self._lock:
            return list(self._reports)

    # --- the pass ---------------------------------------------------------

    @staticmethod
    def _p99_for(
        step_p99: Mapping[str, float], pod_key: str
    ) -> float | None:
        """The pod's exported step p99: exact ``ns/name`` label first,
        then the bare pod name (an engine that only knows its own name
        exports that — same fallback as the CLI's ``engine_row_for``)."""
        v = step_p99.get(pod_key)
        if v is None:
            _, _, bare = pod_key.partition("/")
            v = step_p99.get(bare)
        return v

    def observe(
        self,
        residency: Mapping[int, Mapping[str, str]],
        step_p99: Mapping[str, float],
    ) -> list[InterferenceReport]:
        """One detector pass over gathered inputs (no I/O, no other
        locks): update solo baselines, compute co-residency verdicts,
        export ratio gauges. Returns the pass's reports."""
        # chips each pod resides on (a gang victim is solo only when
        # EVERY member chip is exclusively its own)
        chips_of: dict[str, list[int]] = {}
        for chip, tenants in residency.items():
            for key in tenants:
                chips_of.setdefault(key, []).append(chip)
        solo = {
            key for key, chips in chips_of.items()
            if all(len(residency[c]) == 1 for c in chips)
        }
        reports: list[InterferenceReport] = []
        exported: set[tuple[str, str, str]] = set()
        gauge_rows: list[tuple[str, str, str, float]] = []
        with self._lock:
            self._passes += 1
            for key in chips_of:
                if key in solo:
                    self._solo_streak[key] = self._solo_streak.get(key, 0) + 1
                else:
                    self._solo_streak[key] = 0
                self._absent.pop(key, None)
            for key in set(self._baseline) | set(self._solo_streak):
                if key in chips_of:
                    continue
                gone = self._absent.get(key, 0) + 1
                if gone >= _PRUNE_AFTER_ABSENT:
                    self._baseline.pop(key, None)
                    self._solo_streak.pop(key, None)
                    self._absent.pop(key, None)
                else:
                    self._absent[key] = gone
            for key in solo:
                p99 = self._p99_for(step_p99, key)
                if p99 is None or p99 <= 0:
                    continue
                prev = self._baseline.get(key)
                if prev is not None and p99 < prev:
                    # downward is always safe: a lower p99 cannot be a
                    # contention artifact
                    self._baseline[key] = prev + self._alpha * (p99 - prev)
                    continue
                if self._solo_streak.get(key, 0) < self._cooldown:
                    # the rolling p99 window still carries the last
                    # episode's contended tail — wait it out before
                    # seeding or raising the solo baseline
                    continue
                self._baseline[key] = (
                    p99 if prev is None
                    else prev + self._alpha * (p99 - prev)
                )
            for chip, tenants in sorted(residency.items()):
                if len(tenants) < 2:
                    continue
                for victim, cls in sorted(tenants.items()):
                    if cls != const.WORKLOAD_LATENCY_CRITICAL:
                        continue
                    base = self._baseline.get(victim)
                    p99 = self._p99_for(step_p99, victim)
                    if base is None or base <= 0 or p99 is None or p99 <= 0:
                        continue
                    ratio = p99 / base
                    aggressors = tuple(
                        sorted(k for k in tenants if k != victim)
                    )
                    reports.append(
                        InterferenceReport(
                            chip=chip, victim=victim, victim_class=cls,
                            aggressors=aggressors, ratio=ratio,
                            victim_p99=p99, baseline_p99=base,
                            flagged=ratio >= self.threshold,
                        )
                    )
                    for agg in aggressors:
                        pair = (str(chip), victim, agg)
                        exported.add(pair)
                        gauge_rows.append((*pair, ratio))
            # Zero ONLY pairs actually gone from residency ("resolved").
            # A pair still co-resident but without a verdict this pass
            # (scrape miss, engine restart mid-re-export, pruned
            # baseline) keeps its last exported ratio: losing the signal
            # is not the same as the episode ending, and zeroing it
            # would read as resolved — and flap on flaky scrapes.
            live_pairs = {
                (str(chip), victim, agg)
                for chip, tenants in residency.items()
                for victim in tenants
                for agg in tenants
                if agg != victim
            }
            carried = (self._exported - exported) & live_pairs
            for stale in self._exported - exported - carried:
                gauge_rows.append((*stale, 0.0))
            self._exported = exported | carried
            self._reports = reports
        for chip, victim, aggressor, ratio in gauge_rows:
            self._reg.gauge_set(
                RATIO_GAUGE, ratio, RATIO_HELP,
                chip=chip, victim=victim, aggressor=aggressor,
            )
        return reports

    # --- annotation surface ------------------------------------------------

    def annotation_doc(self, now_unix: float | None = None) -> dict[str, Any]:
        """The node-annotation document for the last pass: per chip, the
        WORST victim verdict (the CLI renders one row per chip)."""
        worst: dict[int, InterferenceReport] = {}
        for r in self.reports():
            cur = worst.get(r.chip)
            if cur is None or r.ratio > cur.ratio:
                worst[r.chip] = r
        return {
            "time_unix": time.time() if now_unix is None else now_unix,
            "threshold": self.threshold,
            "chips": {str(c): r.to_dict() for c, r in sorted(worst.items())},
        }


def interference_from_node(
    node: Mapping[str, Any] | None,
) -> dict[str, Any] | None:
    """Parse the interference node annotation
    (:data:`~..const.ANN_INTERFERENCE`); None when absent/garbled — the
    inspect CLI's read side of :meth:`InterferenceLoop.publish`. Chip
    rows are coerced (garbled ratios read as 0.0) so callers can format
    without re-validating."""
    if not node:
        return None
    raw = ((node.get("metadata") or {}).get("annotations") or {}).get(
        const.ANN_INTERFERENCE
    )
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    chips_raw = doc.get("chips")
    chips: dict[str, dict[str, Any]] = {}
    if isinstance(chips_raw, dict):
        for c, row in chips_raw.items():
            if not isinstance(row, dict):
                continue
            try:
                ratio = float(row.get("ratio", 0.0))
            except (TypeError, ValueError):
                ratio = 0.0
            aggs = row.get("aggressors")
            chips[str(c)] = {
                "victim": str(row.get("victim", "") or ""),
                "aggressors": [str(a) for a in aggs]
                if isinstance(aggs, list) else [],
                "ratio": ratio,
                "flagged": bool(row.get("flagged")),
            }
    try:
        threshold = float(doc.get("threshold", 0.0))
    except (TypeError, ValueError):
        threshold = 0.0
    try:
        # kept so consumers (and -o json) can judge verdict staleness —
        # a dead detector leaves its last annotation behind forever
        time_unix = float(doc.get("time_unix", 0.0))
    except (TypeError, ValueError):
        time_unix = 0.0
    return {"chips": chips, "threshold": threshold, "time_unix": time_unix}


class InterferenceLoop:
    """The daemon's detector driver: every ``interval_s`` it gathers
    residency (pod source) + step p99s (metrics registry), runs one
    detector pass, and publishes the interference node annotation
    best-effort — the same scan/publish shape as
    :class:`~..allocator.defrag.DefragLoop`.

    The signal source, in precedence order: an explicit ``step_p99_fn``
    (tests, custom pipelines), then ``scrape_urls`` (the serving pods'
    ``/metrics`` endpoints — the deployment where engines run in their
    own containers and the daemon's registry never sees their gauges),
    then the shared in-process registry's
    ``tpushare_engine_step_p99_seconds`` series (engines co-located in
    the daemon process — benches, tests, single-process integrations)."""

    def __init__(
        self,
        detector: InterferenceDetector,
        api: Any,
        node_name: str,
        pod_source: Any,
        *,
        interval_s: float = 30.0,
        step_p99_fn: Callable[[], Mapping[str, float]] | None = None,
        scrape_urls: Iterable[str] | None = None,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self._detector = detector
        self._api = api
        self._node = node_name
        self._pods = pod_source
        self._interval = interval_s
        self._reg = registry if registry is not None else REGISTRY
        self._step_fn = step_p99_fn
        self._scrape_urls = list(scrape_urls or ())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "InterferenceLoop":
        self._thread = threading.Thread(
            target=self._run, name="interference-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — never kill the loop
                log.warning("interference pass failed: %s", e)

    def _step_p99s(self) -> Mapping[str, float]:
        if self._step_fn is not None:
            return self._step_fn()
        if self._scrape_urls:
            return step_p99s_from_urls(self._scrape_urls)
        out: dict[str, float] = {}
        for labels, value in self._reg.gauge_series(STEP_P99_GAUGE).items():
            pod = dict(labels).get("pod", "")
            if pod:
                out[pod] = value
        return out

    def run_once(self) -> list[InterferenceReport]:
        """One gather-observe-publish pass (callable directly in tests).

        Residency comes from the pod source's incrementally-maintained
        per-chip index when it has one (``PodInformer.chip_residency``,
        backed by ``NodeChipUsage`` — same membership predicates), else
        from a fresh :func:`residency_from_pods` over the labeled pods
        (list/kubelet-backed sources)."""
        fn = getattr(self._pods, "chip_residency", None)
        if callable(fn):
            residency = fn()
        else:
            residency = residency_from_pods(self._pods.labeled_pods())
        reports = self._detector.observe(residency, self._step_p99s())
        self.publish()
        return reports

    def publish(self) -> None:
        """Write the interference node annotation (best effort — the
        apiserver is the database, the CLI needs no extra endpoint)."""
        doc = self._detector.annotation_doc()
        try:
            self._api.patch_node(
                self._node,
                {"metadata": {"annotations": {
                    const.ANN_INTERFERENCE: json.dumps(doc, sort_keys=True)
                }}},
            )
        except Exception as e:  # noqa: BLE001 — status is observability
            log.v(4, "interference: annotation publish failed (%s)", e)
