"""Minimal kube-apiserver REST client.

No kubernetes client library is vendored; the plugin needs only a handful
of verbs (list pods/nodes with selectors, strategic-merge patch pod, patch
node status), all plain REST+JSON. Config resolution mirrors the reference
(``podmanager.go:29-57``): ``$KUBECONFIG`` file if set, else the in-cluster
serviceaccount (token + CA + ``KUBERNETES_SERVICE_HOST/PORT``).

Transport: the unary verbs ride a persistent per-thread ``http.client``
connection — the Allocate hot path's PATCH is the one unavoidable network
round-trip (``allocate.go:136-150``), and the requests library spends
~0.5 ms of pure client CPU per call (header/cookie plumbing) with a long
jittery tail, roughly 4x the cost of the socket write itself. The
streaming watch keeps requests (chunked iter_lines + a Response handle the
informer can close from another thread to cancel a blocked read).
"""

from __future__ import annotations

import http.client
import json
import os
import ssl
import threading
import urllib.parse
from typing import Any, Callable, Iterator, Mapping

import requests

from ..utils.circuit import CircuitBreaker
from ..utils.faults import FAULTS
from ..utils.log import get_logger
from ..utils.lockrank import make_lock
from ..utils.metric_catalog import (
    PATCH_BATCH_RECORDS,
    PATCH_COALESCED_TOTAL as PATCH_COALESCED,
    PATCH_REQUESTS_TOTAL as PATCH_REQUESTS,
)

log = get_logger("cluster.apiserver")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
STRATEGIC_MERGE = "application/strategic-merge-patch+json"
MERGE_PATCH = "application/merge-patch+json"


class ApiError(RuntimeError):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"apiserver HTTP {status}: {body[:300]}")
        self.status = status
        self.body = body


class ApiServerClient:
    def __init__(
        self,
        base_url: str,
        token: str = "",
        ca_file: str | None = None,
        client_cert: tuple[str, str] | None = None,
        insecure: bool = False,
        timeout_s: float = 10.0,
        breaker: CircuitBreaker | None = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self._timeout = timeout_s
        # One breaker across every verb AND the watch: they share the
        # endpoint, so evidence of an outage from any of them should stop
        # all of them from stacking connect timeouts (see utils.circuit).
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._session = requests.Session()
        # Cluster-internal endpoints only: skip the per-request environment
        # scan for proxies/netrc (~0.3 ms per call on the Allocate path;
        # HTTP(S)_PROXY would break in-cluster traffic anyway).
        self._session.trust_env = False
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        if client_cert:
            self._session.cert = client_cert
        self._session.verify = False if insecure else (ca_file or True)

        # Unary-verb transport: persistent http.client connections, one per
        # thread (HTTPConnection is not thread-safe; the extender serves
        # concurrent webhook verbs over one shared client).
        u = urllib.parse.urlsplit(self.base_url)
        self._scheme = u.scheme or "http"
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if self._scheme == "https" else 80)
        # Path prefix in the server URL (proxied clusters, e.g.
        # https://gw.example/k8s/clusters/c-abc) must prefix every verb.
        self._base_path = u.path.rstrip("/")
        self._headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._ssl_ctx: ssl.SSLContext | None = None
        if self._scheme == "https":
            if insecure:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            else:
                ctx = ssl.create_default_context(cafile=ca_file)
            if client_cert:
                ctx.load_cert_chain(client_cert[0], client_cert[1])
            self._ssl_ctx = ctx
        self._local = threading.local()
        # Lazily-built node-PATCH coalescer (patch_node_merged): one
        # dispatcher thread per client, created only if the merged verb is
        # actually used.
        self._coalescer_init_lock = make_lock("apiserver.coalescer")
        self._node_coalescer: "NodePatchCoalescer | None" = None

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self._scheme == "https":
                conn = http.client.HTTPSConnection(
                    self._host, self._port,
                    context=self._ssl_ctx, timeout=self._timeout,
                )
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
            self._local.conn = conn
        return conn

    def _request(
        self,
        method: str,
        path: str,
        params: Mapping[str, str] | None = None,
        body: str | None = None,
        content_type: str | None = None,
        timeout_s: float | None = None,
    ) -> tuple[int, str]:
        """One unary round-trip, gated by the circuit breaker.

        Transport failures and 5xx responses count against the breaker
        (both mean "the control plane is not serving us"); 2xx/4xx close
        it — a 404 or 409 is the apiserver working as intended.
        ``timeout_s`` overrides the client timeout for this call only
        (callers under an admission deadline can't afford the default).
        """
        self.breaker.before()  # raises CircuitOpenError while open
        try:
            status, text = self._do_request(
                method, path, params, body, content_type, timeout_s
            )
        except Exception:
            self.breaker.record_failure()
            raise
        if status >= 500:
            self.breaker.record_failure()
        else:
            self.breaker.record_success()
        return status, text

    def _do_request(
        self,
        method: str,
        path: str,
        params: Mapping[str, str] | None = None,
        body: str | None = None,
        content_type: str | None = None,
        timeout_s: float | None = None,
    ) -> tuple[int, str]:
        """One unary round-trip on the persistent connection.

        A keep-alive connection the server quietly closed surfaces as a
        failure on the *next* use (write succeeds into a dead socket, read
        gets EOF = ``RemoteDisconnected``) — retried once on a fresh
        connection. Non-idempotent verbs (PATCH/POST) retry ONLY on that
        zero-bytes-received signature or on send-phase failures: a timeout
        mid-response could mean the server already applied the change
        (re-sending a Binding would 409 a pod that is actually bound), so
        it propagates.
        """
        FAULTS.fire("apiserver.request")
        if params:
            path = path + "?" + urllib.parse.urlencode(params)
        path = self._base_path + path
        headers = dict(self._headers)
        if content_type:
            headers["Content-Type"] = content_type
        idempotent = method == "GET"
        for attempt in (0, 1):
            conn = self._connection()
            if timeout_s is not None:
                # Per-call override on the shared per-thread connection:
                # conn.timeout governs the (re)connect, settimeout the
                # reads on a live socket. Restored in the finally so later
                # callers on this thread get the client default back.
                conn.timeout = timeout_s
                if conn.sock is not None:
                    conn.sock.settimeout(timeout_s)
            sent = False
            try:
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                return resp.status, resp.read().decode("utf-8", "replace")
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                self._local.conn = None
                try:
                    conn.close()
                except OSError:  # already dead; we're replacing it anyway
                    pass
                retriable = idempotent or not sent or isinstance(
                    e, http.client.RemoteDisconnected
                )
                if attempt or not retriable:
                    raise
            finally:
                if timeout_s is not None:
                    conn.timeout = self._timeout
                    try:
                        if conn.sock is not None:
                            conn.sock.settimeout(self._timeout)
                    except OSError:  # socket already dead
                        pass

    # --- construction ------------------------------------------------------

    @classmethod
    def from_env(cls, timeout_s: float = 10.0) -> "ApiServerClient":
        """$KUBECONFIG if set, else ~/.kube/config if present, else in-cluster.

        One resolution order for every binary (daemon, extender, CLIs) —
        the reference's CLIs had their own slightly different kubeInit
        (``cmd/inspect/podinfo.go:27-46``), a divergence not worth keeping.
        """
        kubeconfig = os.environ.get("KUBECONFIG", "")
        if kubeconfig and os.path.exists(kubeconfig):
            return cls.from_kubeconfig(kubeconfig, timeout_s=timeout_s)
        default = os.path.expanduser("~/.kube/config")
        if os.path.exists(default):
            return cls.from_kubeconfig(default, timeout_s=timeout_s)
        return cls.in_cluster(timeout_s=timeout_s)

    @classmethod
    def in_cluster(cls, timeout_s: float = 10.0) -> "ApiServerClient":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError(
                "not in cluster: KUBERNETES_SERVICE_HOST unset and no KUBECONFIG"
            )
        token = ""
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        ca = os.path.join(SA_DIR, "ca.crt")
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else None,
            insecure=not os.path.exists(ca),
            timeout_s=timeout_s,
        )

    @classmethod
    def from_kubeconfig(cls, path: str, timeout_s: float = 10.0) -> "ApiServerClient":
        import base64
        import tempfile

        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f) or {}

        def materialize(data_b64: str, suffix: str) -> str:
            """Inline *-data credentials (kind/minikube/GKE kubeconfigs) ->
            temp file, since requests wants paths."""
            f = tempfile.NamedTemporaryFile(
                mode="wb", suffix=suffix, delete=False, prefix="tpushare-kc-"
            )
            f.write(base64.b64decode(data_b64))
            f.close()
            return f.name

        ctx_name = cfg.get("current-context", "")
        ctx = {}
        for item in cfg.get("contexts", []) or []:
            if item.get("name") == ctx_name:
                ctx = item.get("context", {}) or {}
        cluster = {}
        for item in cfg.get("clusters", []) or []:
            if item.get("name") == ctx.get("cluster"):
                cluster = item.get("cluster", {}) or {}
        user = {}
        for item in cfg.get("users", []) or []:
            if item.get("name") == ctx.get("user"):
                user = item.get("user", {}) or {}

        server = cluster.get("server", "https://127.0.0.1:6443")
        insecure = bool(cluster.get("insecure-skip-tls-verify", False))
        ca_file = cluster.get("certificate-authority")
        if not ca_file and cluster.get("certificate-authority-data"):
            ca_file = materialize(cluster["certificate-authority-data"], ".crt")
        token = user.get("token", "")
        cert_file = user.get("client-certificate")
        key_file = user.get("client-key")
        if not cert_file and user.get("client-certificate-data"):
            cert_file = materialize(user["client-certificate-data"], ".crt")
        if not key_file and user.get("client-key-data"):
            key_file = materialize(user["client-key-data"], ".key")
        cert = (cert_file, key_file) if cert_file and key_file else None
        return cls(
            server,
            token=token,
            ca_file=ca_file,
            client_cert=cert,
            insecure=insecure,
            timeout_s=timeout_s,
        )

    # --- raw verbs ----------------------------------------------------------

    def _get(
        self,
        path: str,
        params: Mapping[str, str] | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        status, text = self._request("GET", path, params, timeout_s=timeout_s)
        if status != 200:
            raise ApiError(status, text)
        return json.loads(text)

    def _patch(self, path: str, body: Any, content_type: str) -> dict:
        status, text = self._request("PATCH", path, body=json.dumps(body), content_type=content_type)
        if status not in (200, 201):
            raise ApiError(status, text)
        return json.loads(text)

    # --- typed helpers ------------------------------------------------------

    def list_pods(
        self,
        namespace: str | None = None,
        field_selector: str = "",
        label_selector: str = "",
    ) -> list[dict]:
        # Any falsy namespace ("" or None) means all namespaces — "" must
        # not build the malformed path /api/v1/namespaces//pods.
        if not namespace:
            return self.list_pods_with_rv(field_selector, label_selector)[0]
        params = {}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        return self._get(
            f"/api/v1/namespaces/{namespace}/pods", params
        ).get("items", [])

    def list_pods_with_rv(
        self,
        field_selector: str = "",
        label_selector: str = "",
        timeout_s: float | None = None,
    ) -> tuple[list[dict], str]:
        """LIST returning (items, collection resourceVersion) — the seed for
        a subsequent watch. ``timeout_s`` bounds this one call (the
        informer's Allocate-path refresh runs under a deadline)."""
        params = {}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        body = self._get("/api/v1/pods", params, timeout_s=timeout_s)
        return body.get("items", []), body.get("metadata", {}).get(
            "resourceVersion", "0"
        )

    def watch_pods(
        self,
        resource_version: str = "0",
        field_selector: str = "",
        label_selector: str = "",
        on_response: Callable[[Any], None] | None = None,
    ) -> Iterator[tuple[str, dict]]:
        """Streamed watch: yields (event_type, pod) one at a time until the
        server closes the connection. Compatibility wrapper over
        ``watch_pods_batched`` — consumers that can apply events in bulk
        (the informer) should use the batched form directly."""
        for batch in self.watch_pods_batched(
            resource_version=resource_version,
            field_selector=field_selector,
            label_selector=label_selector,
            on_response=on_response,
        ):
            yield from batch

    def watch_pods_batched(
        self,
        resource_version: str = "0",
        field_selector: str = "",
        label_selector: str = "",
        on_response: Callable[[Any], None] | None = None,
    ) -> Iterator[list[tuple[str, dict]]]:
        """Streamed watch yielding LISTS of (event_type, pod): every event
        decoded from one transport read is one batch. An idle watch yields
        singletons; a PATCH burst arrives as several lines in one read (the
        kernel buffers while the consumer processes the previous batch), so
        bursts coalesce naturally and the informer can apply each batch
        under a single cache-lock acquisition. Raises ApiError on non-200
        (e.g. 410 Gone -> relist).

        ``on_response`` (if given) receives the live ``requests.Response``
        so the caller can ``close()`` it from another thread to cancel the
        blocking read (the informer's stop path).
        """
        params = {"watch": "true", "resourceVersion": resource_version}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        # Stream *establishment* rides the breaker (it dials the same
        # endpoint as the unary verbs); mid-stream failures don't — a
        # server closing an hours-old watch is routine, not an outage.
        self.breaker.before()
        try:
            FAULTS.fire("apiserver.watch")
            r = self._session.get(
                self.base_url + "/api/v1/pods",
                params=params,
                stream=True,
                # (connect, read) — the read timeout bounds a silent watch;
                # the informer treats it like a server hangup and re-watches.
                timeout=(self._timeout, max(self._timeout, 30.0)),
            )
        except Exception:
            self.breaker.record_failure()
            raise
        if r.status_code != 200:
            body = r.text
            r.close()
            if r.status_code >= 500:
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
            raise ApiError(r.status_code, body)
        self.breaker.record_success()
        if on_response is not None:
            on_response(r)
        buf = b""
        try:
            for chunk in r.iter_content(chunk_size=65536):
                if not chunk:
                    continue
                buf += chunk
                if b"\n" not in buf:
                    continue  # partial line: wait for the rest
                complete, _, buf = buf.rpartition(b"\n")
                batch = []
                for line in complete.split(b"\n"):
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    batch.append((evt.get("type", ""), evt.get("object", {})))
                if batch:
                    yield batch
        finally:
            r.close()

    def get_pod(self, namespace: str, name: str) -> dict:
        return self._get(f"/api/v1/namespaces/{namespace}/pods/{name}")

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        """Strategic-merge patch (reference: ``allocate.go:136-150``)."""
        return self._patch(
            f"/api/v1/namespaces/{namespace}/pods/{name}", patch, STRATEGIC_MERGE
        )

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        """POST pods/{name}/binding — used by the scheduler extender."""
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node},
        }
        status, text = self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            body=json.dumps(body),
            content_type="application/json",
        )
        if status not in (200, 201):
            raise ApiError(status, text)

    def list_nodes(self, label_selector: str = "") -> list[dict]:
        params = {"labelSelector": label_selector} if label_selector else {}
        return self._get("/api/v1/nodes", params).get("items", [])

    def get_node(self, name: str) -> dict:
        return self._get(f"/api/v1/nodes/{name}")

    def patch_node(self, name: str, patch: dict) -> dict:
        """Strategic-merge patch on node metadata (fencing-generation
        annotation, allocator/checkpoint.py)."""
        return self._patch(f"/api/v1/nodes/{name}", patch, STRATEGIC_MERGE)

    def patch_node_status(self, name: str, capacity: Mapping[str, str]) -> dict:
        """Merge extended resources into node Status.Capacity/Allocatable.

        Reference: ``patchGPUCount`` via nodeutil.PatchNodeStatus
        (``podmanager.go:74-99``).
        """
        body = {
            "status": {
                "capacity": dict(capacity),
                "allocatable": dict(capacity),
            }
        }
        return self._patch(f"/api/v1/nodes/{name}/status", body, MERGE_PATCH)

    def create_event(self, namespace: str, event: dict) -> None:
        status, _ = self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/events",
            body=json.dumps(event),
            content_type="application/json",
        )
        if status not in (200, 201):
            log.warning("event create failed: HTTP %s", status)

    # --- coalesced writes ---------------------------------------------------

    def patch_node_merged(self, name: str, patch: dict) -> dict:
        """Coalesced ``patch_node``: concurrent metadata updates for the
        same node object merge into ONE strategic-merge PATCH (last writer
        wins per key, submit order preserved); every caller blocks until
        the merged PATCH has landed and gets the server's response."""
        with self._coalescer_init_lock:
            if self._node_coalescer is None:
                self._node_coalescer = NodePatchCoalescer(self)
        return self._node_coalescer.patch_node(name, patch)


# --- PATCH coalescing -------------------------------------------------------

PATCH_BATCH_RECORDS_HELP = (
    "PATCHes dispatched per coalescer flush (group-commit batch-size "
    "distribution for apiserver writes)"
)
PATCH_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
PATCH_COALESCED_HELP = (
    "apiserver PATCH requests saved by coalescing: same-node metadata "
    "updates merged into one request (kind=node)"
)
PATCH_REQUESTS_HELP = (
    "Pod PATCH requests by transport: pipelined (batched on a shared "
    "keep-alive connection) vs sequential (single-item flush or fallback "
    "after a pipeline transport failure)"
)


def _deep_merge(base: dict, overlay: dict) -> dict:
    """Strategic-merge-shaped dict merge: nested dicts merge recursively,
    scalars/lists overwrite (later submission wins — the same outcome two
    sequential PATCHes would have produced)."""
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class NodePatchCoalescer:
    """Group-commit for node-object metadata PATCHes: every update queued
    within one gather window collapses per node into a single merged
    strategic-merge PATCH. Callers keep synchronous semantics (block until
    the merged PATCH lands, receive the response, see the exception)."""

    def __init__(self, client: "ApiServerClient", window_s: float = 0.002) -> None:
        from ..utils.batch import GroupBatcher

        self._c = client
        self._batcher = GroupBatcher(
            self._flush, window_s=window_s, name="node-patch-coalescer"
        )

    def patch_node(self, name: str, patch: dict) -> dict:
        return self._batcher.submit((name, patch)).wait()

    def stop(self) -> None:
        self._batcher.stop()

    def _flush(self, items: list[tuple[str, dict]]) -> list:
        from ..utils.metrics import REGISTRY

        merged: dict[str, dict] = {}
        for name, patch in items:
            merged[name] = _deep_merge(merged.get(name, {}), patch)
        responses: dict[str, object] = {}
        for name, patch in merged.items():
            try:
                responses[name] = self._c.patch_node(name, patch)
            except Exception as e:  # noqa: BLE001 — per-item verdicts
                responses[name] = e
        saved = len(items) - len(merged)
        if saved:
            REGISTRY.counter_inc(
                PATCH_COALESCED, PATCH_COALESCED_HELP,
                value=float(saved), kind="node",
            )
        return [responses[name] for name, _patch in items]


class _SharedReaderSock:
    """Socket shim handing ``http.client.HTTPResponse`` a SHARED buffered
    reader: each response object must consume exactly its bytes from one
    stream (a fresh ``makefile()`` per response would strand pipelined
    bytes in an abandoned buffer)."""

    class _NoClose:
        def __init__(self, fp):
            self._fp = fp

        def close(self):  # HTTPResponse.close() must not kill the stream
            pass

        def flush(self):
            pass

        def __getattr__(self, name):
            return getattr(self._fp, name)

    def __init__(self, fp):
        self._fp = fp

    def makefile(self, *args, **kwargs):
        return self._NoClose(self._fp)


class PodPatchPipeline:
    """Coalesced pod-annotation PATCH dispatcher — the admission pipeline's
    write stage. Concurrently-committed admissions hand their (distinct-pod)
    PATCHes to one dispatcher; each gathered batch is sent **pipelined**
    over a small set of persistent connections (all requests written
    back-to-back, then all responses read in order), amortizing per-request
    client overhead and connection round-trips across the batch. Callers
    block on a per-batch ticket and get exactly the response (or ApiError)
    a direct ``patch_pod`` would have produced; WAL commits that depend on
    the PATCH therefore still strictly follow it.

    Fallback discipline: any transport trouble on the pipelined path drops
    the affected connection and re-issues the unanswered PATCHes one at a
    time through the ordinary client (which owns retry/breaker semantics) —
    strategic-merge annotation PATCHes are safe to re-send. Single-item
    batches skip the pipeline entirely.
    """

    def __init__(
        self,
        client: "ApiServerClient",
        window_s: float = 0.002,
        fanout: int = 4,
    ) -> None:
        from ..utils.batch import GroupBatcher
        from ..utils.metrics import REGISTRY

        self._c = client
        self._fanout = max(1, fanout)
        self._pipes: list[tuple | None] = [None] * self._fanout
        self._batcher = GroupBatcher(
            self._flush,
            window_s=window_s,
            name="pod-patch-pipeline",
            on_batch=lambda n: REGISTRY.observe(
                PATCH_BATCH_RECORDS, float(n), PATCH_BATCH_RECORDS_HELP,
                buckets=PATCH_BATCH_BUCKETS, kind="pod",
            ),
        )

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        return self._batcher.submit((namespace, name, patch)).wait()

    def flush(self, timeout_s: float | None = 5.0) -> None:
        self._batcher.flush(timeout=timeout_s)

    def stop(self) -> None:
        self._batcher.stop()
        for i, pipe in enumerate(self._pipes):
            if pipe is not None:
                try:
                    pipe[0].close()
                except OSError:  # teardown race: already closed
                    pass
                self._pipes[i] = None

    # --- dispatcher internals --------------------------------------------

    def _flush(self, items: list[tuple[str, str, dict]]) -> list:
        from ..utils.metrics import REGISTRY

        self._c.breaker.before()  # open circuit fails the whole batch fast
        if len(items) == 1:
            return [self._sequential(*items[0])]
        results: list = [None] * len(items)
        # round-robin the batch over the pipe slots so the server works
        # the shards in parallel while each shard amortizes its RTTs
        shards: list[list[int]] = [[] for _ in range(min(self._fanout, len(items)))]
        for i in range(len(items)):
            shards[i % len(shards)].append(i)
        saw_5xx = False
        # Two phases: every shard's requests go out back-to-back BEFORE any
        # response is read, so the server processes all fanout connections
        # concurrently while this thread drains them in turn — reading
        # shard 0 to completion first would serialize the whole batch.
        sent = [
            self._send_shard(slot, [(i, items[i]) for i in indexes], results)
            for slot, indexes in enumerate(shards)
        ]
        for slot, indexes in enumerate(shards):
            answered = self._read_shard(
                slot, [(i, items[i]) for i in indexes], results, sent[slot]
            )
            for i in indexes[answered:]:
                if results[i] is None:  # faulted items already have verdicts
                    results[i] = self._sequential(*items[i])
        for r in results:
            if isinstance(r, ApiError) and r.status >= 500:
                saw_5xx = True
        if saw_5xx:
            self._c.breaker.record_failure()
        else:
            self._c.breaker.record_success()
        return results

    def _sequential(self, ns: str, name: str, patch: dict):
        from ..utils.metrics import REGISTRY

        REGISTRY.counter_inc(
            PATCH_REQUESTS, PATCH_REQUESTS_HELP, transport="sequential"
        )
        try:
            return self._c.patch_pod(ns, name, patch)
        except Exception as e:  # noqa: BLE001 — per-item verdicts
            return e

    def _pipe(self, slot: int):
        pipe = self._pipes[slot]
        if pipe is None:
            c = self._c
            if c._scheme == "https":
                conn = http.client.HTTPSConnection(
                    c._host, c._port, context=c._ssl_ctx, timeout=c._timeout
                )
            else:
                conn = http.client.HTTPConnection(
                    c._host, c._port, timeout=c._timeout
                )
            conn.connect()
            pipe = (conn, conn.sock.makefile("rb"))
            self._pipes[slot] = pipe
        return pipe

    def _drop_pipe(self, slot: int) -> None:
        pipe = self._pipes[slot]
        self._pipes[slot] = None
        if pipe is not None:
            try:
                pipe[1].close()
            except OSError:  # teardown race: already closed
                pass
            try:
                pipe[0].close()
            except OSError:  # teardown race: already closed
                pass

    def _send_shard(
        self, slot: int, shard: list[tuple[int, tuple[str, str, dict]]],
        results: list,
    ) -> list[int] | None:
        """Write every PATCH in ``shard`` back-to-back on the slot's
        connection. Returns the positions actually sent (fault-injected
        items get their verdicts recorded and are skipped), or None when
        the pipe was dead at send time (caller falls back sequentially).
        Fault-point and ApiError semantics match the unary client's."""
        c = self._c
        live: list[int] = []  # positions in `shard` actually sent
        requests_bytes: list[bytes] = []
        for pos, (i, (ns, name, patch)) in enumerate(shard):
            try:
                FAULTS.fire("apiserver.request")
            except Exception as e:  # noqa: BLE001 — injected per-item fault
                results[i] = e
                continue
            body = json.dumps(patch).encode()
            path = f"{c._base_path}/api/v1/namespaces/{ns}/pods/{name}"
            head = (
                f"PATCH {path} HTTP/1.1\r\n"
                f"Host: {c._host}:{c._port}\r\n"
                f"Content-Type: {STRATEGIC_MERGE}\r\n"
                f"Content-Length: {len(body)}\r\n"
            )
            for hk, hv in c._headers.items():
                head += f"{hk}: {hv}\r\n"
            requests_bytes.append(head.encode() + b"\r\n" + body)
            live.append(pos)
        if not live:
            return live
        try:
            conn, _fp = self._pipe(slot)
            conn.sock.sendall(b"".join(requests_bytes))
        except Exception:  # noqa: BLE001 — dead pipe: caller falls back
            self._drop_pipe(slot)
            return None
        return live

    def _read_shard(
        self, slot: int, shard: list[tuple[int, tuple[str, str, dict]]],
        results: list, live: list[int] | None,
    ) -> int:
        """Read the responses for a shard ``_send_shard`` wrote. Returns
        how many shard positions are fully resolved; the caller re-issues
        the rest sequentially."""
        if live is None:
            return 0  # send failed outright: everything falls back
        if not live:
            return len(shard)  # nothing was sent (all faulted, verdicts set)
        from ..utils.metrics import REGISTRY

        pipe = self._pipes[slot]
        if pipe is None:
            return live[0]
        fp = pipe[1]
        close_after = False
        for pos in live:
            i = shard[pos][0]
            try:
                resp = http.client.HTTPResponse(
                    _SharedReaderSock(fp), method="PATCH"
                )
                resp.begin()
                data = resp.read()
                close_after = close_after or resp.will_close
            except Exception:  # noqa: BLE001 — torn stream mid-pipeline
                self._drop_pipe(slot)
                return pos  # this item and the rest go sequential
            REGISTRY.counter_inc(
                PATCH_REQUESTS, PATCH_REQUESTS_HELP, transport="pipelined"
            )
            if resp.status in (200, 201):
                try:
                    results[i] = json.loads(data)
                except ValueError as e:
                    results[i] = ApiError(
                        resp.status,
                        data.decode("utf-8", "replace")[:300]
                        + f" (bad json: {e})",
                    )
            else:
                results[i] = ApiError(resp.status, data.decode("utf-8", "replace"))
        if close_after:
            self._drop_pipe(slot)
        return len(shard)
