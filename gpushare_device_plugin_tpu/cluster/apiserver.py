"""Minimal kube-apiserver REST client.

No kubernetes client library is vendored; the plugin needs only a handful
of verbs (list pods/nodes with selectors, strategic-merge patch pod, patch
node status), all plain REST+JSON. Config resolution mirrors the reference
(``podmanager.go:29-57``): ``$KUBECONFIG`` file if set, else the in-cluster
serviceaccount (token + CA + ``KUBERNETES_SERVICE_HOST/PORT``).

Transport: the unary verbs ride a persistent per-thread ``http.client``
connection — the Allocate hot path's PATCH is the one unavoidable network
round-trip (``allocate.go:136-150``), and the requests library spends
~0.5 ms of pure client CPU per call (header/cookie plumbing) with a long
jittery tail, roughly 4x the cost of the socket write itself. The
streaming watch keeps requests (chunked iter_lines + a Response handle the
informer can close from another thread to cancel a blocked read).
"""

from __future__ import annotations

import http.client
import json
import os
import ssl
import threading
import urllib.parse
from typing import Any, Mapping

import requests

from ..utils.circuit import CircuitBreaker
from ..utils.faults import FAULTS
from ..utils.log import get_logger

log = get_logger("cluster.apiserver")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
STRATEGIC_MERGE = "application/strategic-merge-patch+json"
MERGE_PATCH = "application/merge-patch+json"


class ApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"apiserver HTTP {status}: {body[:300]}")
        self.status = status
        self.body = body


class ApiServerClient:
    def __init__(
        self,
        base_url: str,
        token: str = "",
        ca_file: str | None = None,
        client_cert: tuple[str, str] | None = None,
        insecure: bool = False,
        timeout_s: float = 10.0,
        breaker: CircuitBreaker | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self._timeout = timeout_s
        # One breaker across every verb AND the watch: they share the
        # endpoint, so evidence of an outage from any of them should stop
        # all of them from stacking connect timeouts (see utils.circuit).
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._session = requests.Session()
        # Cluster-internal endpoints only: skip the per-request environment
        # scan for proxies/netrc (~0.3 ms per call on the Allocate path;
        # HTTP(S)_PROXY would break in-cluster traffic anyway).
        self._session.trust_env = False
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        if client_cert:
            self._session.cert = client_cert
        self._session.verify = False if insecure else (ca_file or True)

        # Unary-verb transport: persistent http.client connections, one per
        # thread (HTTPConnection is not thread-safe; the extender serves
        # concurrent webhook verbs over one shared client).
        u = urllib.parse.urlsplit(self.base_url)
        self._scheme = u.scheme or "http"
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if self._scheme == "https" else 80)
        # Path prefix in the server URL (proxied clusters, e.g.
        # https://gw.example/k8s/clusters/c-abc) must prefix every verb.
        self._base_path = u.path.rstrip("/")
        self._headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._ssl_ctx: ssl.SSLContext | None = None
        if self._scheme == "https":
            if insecure:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            else:
                ctx = ssl.create_default_context(cafile=ca_file)
            if client_cert:
                ctx.load_cert_chain(client_cert[0], client_cert[1])
            self._ssl_ctx = ctx
        self._local = threading.local()

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self._scheme == "https":
                conn = http.client.HTTPSConnection(
                    self._host, self._port,
                    context=self._ssl_ctx, timeout=self._timeout,
                )
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
            self._local.conn = conn
        return conn

    def _request(
        self,
        method: str,
        path: str,
        params: Mapping[str, str] | None = None,
        body: str | None = None,
        content_type: str | None = None,
        timeout_s: float | None = None,
    ) -> tuple[int, str]:
        """One unary round-trip, gated by the circuit breaker.

        Transport failures and 5xx responses count against the breaker
        (both mean "the control plane is not serving us"); 2xx/4xx close
        it — a 404 or 409 is the apiserver working as intended.
        ``timeout_s`` overrides the client timeout for this call only
        (callers under an admission deadline can't afford the default).
        """
        self.breaker.before()  # raises CircuitOpenError while open
        try:
            status, text = self._do_request(
                method, path, params, body, content_type, timeout_s
            )
        except Exception:
            self.breaker.record_failure()
            raise
        if status >= 500:
            self.breaker.record_failure()
        else:
            self.breaker.record_success()
        return status, text

    def _do_request(
        self,
        method: str,
        path: str,
        params: Mapping[str, str] | None = None,
        body: str | None = None,
        content_type: str | None = None,
        timeout_s: float | None = None,
    ) -> tuple[int, str]:
        """One unary round-trip on the persistent connection.

        A keep-alive connection the server quietly closed surfaces as a
        failure on the *next* use (write succeeds into a dead socket, read
        gets EOF = ``RemoteDisconnected``) — retried once on a fresh
        connection. Non-idempotent verbs (PATCH/POST) retry ONLY on that
        zero-bytes-received signature or on send-phase failures: a timeout
        mid-response could mean the server already applied the change
        (re-sending a Binding would 409 a pod that is actually bound), so
        it propagates.
        """
        FAULTS.fire("apiserver.request")
        if params:
            path = path + "?" + urllib.parse.urlencode(params)
        path = self._base_path + path
        headers = dict(self._headers)
        if content_type:
            headers["Content-Type"] = content_type
        idempotent = method == "GET"
        for attempt in (0, 1):
            conn = self._connection()
            if timeout_s is not None:
                # Per-call override on the shared per-thread connection:
                # conn.timeout governs the (re)connect, settimeout the
                # reads on a live socket. Restored in the finally so later
                # callers on this thread get the client default back.
                conn.timeout = timeout_s
                if conn.sock is not None:
                    conn.sock.settimeout(timeout_s)
            sent = False
            try:
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                return resp.status, resp.read().decode("utf-8", "replace")
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                self._local.conn = None
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                retriable = idempotent or not sent or isinstance(
                    e, http.client.RemoteDisconnected
                )
                if attempt or not retriable:
                    raise
            finally:
                if timeout_s is not None:
                    conn.timeout = self._timeout
                    try:
                        if conn.sock is not None:
                            conn.sock.settimeout(self._timeout)
                    except Exception:  # noqa: BLE001 — socket already dead
                        pass

    # --- construction ------------------------------------------------------

    @classmethod
    def from_env(cls, timeout_s: float = 10.0) -> "ApiServerClient":
        """$KUBECONFIG if set, else ~/.kube/config if present, else in-cluster.

        One resolution order for every binary (daemon, extender, CLIs) —
        the reference's CLIs had their own slightly different kubeInit
        (``cmd/inspect/podinfo.go:27-46``), a divergence not worth keeping.
        """
        kubeconfig = os.environ.get("KUBECONFIG", "")
        if kubeconfig and os.path.exists(kubeconfig):
            return cls.from_kubeconfig(kubeconfig, timeout_s=timeout_s)
        default = os.path.expanduser("~/.kube/config")
        if os.path.exists(default):
            return cls.from_kubeconfig(default, timeout_s=timeout_s)
        return cls.in_cluster(timeout_s=timeout_s)

    @classmethod
    def in_cluster(cls, timeout_s: float = 10.0) -> "ApiServerClient":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError(
                "not in cluster: KUBERNETES_SERVICE_HOST unset and no KUBECONFIG"
            )
        token = ""
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        ca = os.path.join(SA_DIR, "ca.crt")
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else None,
            insecure=not os.path.exists(ca),
            timeout_s=timeout_s,
        )

    @classmethod
    def from_kubeconfig(cls, path: str, timeout_s: float = 10.0) -> "ApiServerClient":
        import base64
        import tempfile

        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f) or {}

        def materialize(data_b64: str, suffix: str) -> str:
            """Inline *-data credentials (kind/minikube/GKE kubeconfigs) ->
            temp file, since requests wants paths."""
            f = tempfile.NamedTemporaryFile(
                mode="wb", suffix=suffix, delete=False, prefix="tpushare-kc-"
            )
            f.write(base64.b64decode(data_b64))
            f.close()
            return f.name

        ctx_name = cfg.get("current-context", "")
        ctx = {}
        for item in cfg.get("contexts", []) or []:
            if item.get("name") == ctx_name:
                ctx = item.get("context", {}) or {}
        cluster = {}
        for item in cfg.get("clusters", []) or []:
            if item.get("name") == ctx.get("cluster"):
                cluster = item.get("cluster", {}) or {}
        user = {}
        for item in cfg.get("users", []) or []:
            if item.get("name") == ctx.get("user"):
                user = item.get("user", {}) or {}

        server = cluster.get("server", "https://127.0.0.1:6443")
        insecure = bool(cluster.get("insecure-skip-tls-verify", False))
        ca_file = cluster.get("certificate-authority")
        if not ca_file and cluster.get("certificate-authority-data"):
            ca_file = materialize(cluster["certificate-authority-data"], ".crt")
        token = user.get("token", "")
        cert_file = user.get("client-certificate")
        key_file = user.get("client-key")
        if not cert_file and user.get("client-certificate-data"):
            cert_file = materialize(user["client-certificate-data"], ".crt")
        if not key_file and user.get("client-key-data"):
            key_file = materialize(user["client-key-data"], ".key")
        cert = (cert_file, key_file) if cert_file and key_file else None
        return cls(
            server,
            token=token,
            ca_file=ca_file,
            client_cert=cert,
            insecure=insecure,
            timeout_s=timeout_s,
        )

    # --- raw verbs ----------------------------------------------------------

    def _get(
        self,
        path: str,
        params: Mapping[str, str] | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        status, text = self._request("GET", path, params, timeout_s=timeout_s)
        if status != 200:
            raise ApiError(status, text)
        return json.loads(text)

    def _patch(self, path: str, body: Any, content_type: str) -> dict:
        status, text = self._request("PATCH", path, body=json.dumps(body), content_type=content_type)
        if status not in (200, 201):
            raise ApiError(status, text)
        return json.loads(text)

    # --- typed helpers ------------------------------------------------------

    def list_pods(
        self,
        namespace: str | None = None,
        field_selector: str = "",
        label_selector: str = "",
    ) -> list[dict]:
        # Any falsy namespace ("" or None) means all namespaces — "" must
        # not build the malformed path /api/v1/namespaces//pods.
        if not namespace:
            return self.list_pods_with_rv(field_selector, label_selector)[0]
        params = {}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        return self._get(
            f"/api/v1/namespaces/{namespace}/pods", params
        ).get("items", [])

    def list_pods_with_rv(
        self,
        field_selector: str = "",
        label_selector: str = "",
        timeout_s: float | None = None,
    ) -> tuple[list[dict], str]:
        """LIST returning (items, collection resourceVersion) — the seed for
        a subsequent watch. ``timeout_s`` bounds this one call (the
        informer's Allocate-path refresh runs under a deadline)."""
        params = {}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        body = self._get("/api/v1/pods", params, timeout_s=timeout_s)
        return body.get("items", []), body.get("metadata", {}).get(
            "resourceVersion", "0"
        )

    def watch_pods(
        self,
        resource_version: str = "0",
        field_selector: str = "",
        label_selector: str = "",
        on_response=None,
    ):
        """Streamed watch: yields (event_type, pod) until the server closes
        the connection. Raises ApiError on non-200 (e.g. 410 Gone -> relist).

        ``on_response`` (if given) receives the live ``requests.Response``
        so the caller can ``close()`` it from another thread to cancel the
        blocking read (the informer's stop path).
        """
        params = {"watch": "true", "resourceVersion": resource_version}
        if field_selector:
            params["fieldSelector"] = field_selector
        if label_selector:
            params["labelSelector"] = label_selector
        # Stream *establishment* rides the breaker (it dials the same
        # endpoint as the unary verbs); mid-stream failures don't — a
        # server closing an hours-old watch is routine, not an outage.
        self.breaker.before()
        try:
            FAULTS.fire("apiserver.watch")
            r = self._session.get(
                self.base_url + "/api/v1/pods",
                params=params,
                stream=True,
                # (connect, read) — the read timeout bounds a silent watch;
                # the informer treats it like a server hangup and re-watches.
                timeout=(self._timeout, max(self._timeout, 30.0)),
            )
        except Exception:
            self.breaker.record_failure()
            raise
        if r.status_code != 200:
            body = r.text
            r.close()
            if r.status_code >= 500:
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
            raise ApiError(r.status_code, body)
        self.breaker.record_success()
        if on_response is not None:
            on_response(r)
        try:
            for line in r.iter_lines():
                if not line:
                    continue
                evt = json.loads(line)
                yield evt.get("type", ""), evt.get("object", {})
        finally:
            r.close()

    def get_pod(self, namespace: str, name: str) -> dict:
        return self._get(f"/api/v1/namespaces/{namespace}/pods/{name}")

    def patch_pod(self, namespace: str, name: str, patch: dict) -> dict:
        """Strategic-merge patch (reference: ``allocate.go:136-150``)."""
        return self._patch(
            f"/api/v1/namespaces/{namespace}/pods/{name}", patch, STRATEGIC_MERGE
        )

    def bind_pod(self, namespace: str, name: str, node: str) -> None:
        """POST pods/{name}/binding — used by the scheduler extender."""
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node},
        }
        status, text = self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            body=json.dumps(body),
            content_type="application/json",
        )
        if status not in (200, 201):
            raise ApiError(status, text)

    def list_nodes(self, label_selector: str = "") -> list[dict]:
        params = {"labelSelector": label_selector} if label_selector else {}
        return self._get("/api/v1/nodes", params).get("items", [])

    def get_node(self, name: str) -> dict:
        return self._get(f"/api/v1/nodes/{name}")

    def patch_node(self, name: str, patch: dict) -> dict:
        """Strategic-merge patch on node metadata (fencing-generation
        annotation, allocator/checkpoint.py)."""
        return self._patch(f"/api/v1/nodes/{name}", patch, STRATEGIC_MERGE)

    def patch_node_status(self, name: str, capacity: Mapping[str, str]) -> dict:
        """Merge extended resources into node Status.Capacity/Allocatable.

        Reference: ``patchGPUCount`` via nodeutil.PatchNodeStatus
        (``podmanager.go:74-99``).
        """
        body = {
            "status": {
                "capacity": dict(capacity),
                "allocatable": dict(capacity),
            }
        }
        return self._patch(f"/api/v1/nodes/{name}/status", body, MERGE_PATCH)

    def create_event(self, namespace: str, event: dict) -> None:
        status, _ = self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/events",
            body=json.dumps(event),
            content_type="application/json",
        )
        if status not in (200, 201):
            log.warning("event create failed: HTTP %s", status)
