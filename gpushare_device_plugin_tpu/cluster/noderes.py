"""Shared node-resource parsing used by the inspect CLI and the extender.

One definition of "per-chip capacity" so placement math and utilization
reports cannot diverge: total allocatable units split uniformly across the
advertised chip count (chips are homogeneous within a node on TPU-VMs).
"""

from __future__ import annotations


def chip_capacity_vector(node: dict, resource: str, count_resource: str) -> dict[int, int]:
    """chip index -> units, or {} when the node doesn't advertise ``resource``."""
    status = node.get("status", {})
    try:
        total = int(str(status.get("allocatable", {}).get(resource, "0")))
        chips = int(str(status.get("capacity", {}).get(count_resource, "0")))
    except ValueError:
        return {}
    if total <= 0:
        return {}
    if chips <= 0:
        chips = 1
    per = total // chips
    return {i: per for i in range(chips)}
