"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support for workloads scheduled by the plugin: the sequence is
sharded over the ``sp`` mesh axis; K/V blocks rotate around the ring via
``jax.lax.ppermute`` (ICI neighbor exchange on TPU) while each device keeps
its Q block and maintains an online-softmax accumulator — so attention over
a sequence of length S costs O(S/n) memory per chip and the K/V transfer
overlaps with the block matmuls (MXU work) under XLA's async collectives.

This is compiler-friendly by construction: a `lax.fori_loop` of static
trip-count ``n`` (the sp axis size), static block shapes, no host control
flow. The reference has no long-context machinery at all (SURVEY.md
section 5: absent); this is the TPU-native capability its workloads need.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _online_update(o, m, l, s, v):
    """One online-softmax accumulation step (GQA-grouped shapes).

    o: [B,Hkv,g,Tq,D] weighted-value accumulator, m: [B,Hkv,g,Tq] running
    max, l: [B,Hkv,g,Tq] running denominator, s: [B,Hkv,g,Tq,Tk] scores
    (may be -inf), v: [B,Tk,Hkv,D]. MHA is the g=1 case.
    """
    s_max = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, s_max)
    # Rows fully masked so far have m_new == -inf; substitute 0 so the exps
    # below produce exact zeros instead of NaN ((-inf) - (-inf)).
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])  # [B,Hkv,g,Tq,Tk]
    alpha = jnp.exp(m - m_safe)  # m_safe is finite, so m=-inf -> alpha=0
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = alpha[..., None] * o + jnp.einsum("bhgqk,bkhd->bhgqd", p, v)
    return o_new, m_new, l_new


def _ring_uses_kernel(Tq: int, Tk: int, hop_attention: str) -> bool:
    """THE flash-hop gate — the single predicate both the per-shard block
    and the ``ring_attention`` wrapper (its check_vma decision) consult,
    so they can never diverge; block fit defers to the kernel module's
    own ``fits_kernel`` (one copy repo-wide)."""
    from ..ops.flash_attention import fits_kernel

    if hop_attention not in ("auto", "plain", "flash"):
        raise ValueError(
            f"unknown hop_attention={hop_attention!r}: expected auto|plain|flash"
        )
    if hop_attention == "flash":
        return True
    if hop_attention == "plain":
        return False
    return (
        jax.default_backend() == "tpu" and Tq == Tk and fits_kernel(Tq)
    )


def ring_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
    hop_attention: str = "auto",
) -> jax.Array:
    """Per-shard ring attention body — call *inside* ``shard_map``.

    q: [B, Tq, H, D] local query block; k, v: [B, Tk, Hkv, D] local K/V
    block. **GQA-native**: ``Hkv`` may divide ``H`` (KV head i serves
    query heads [i*g, (i+1)*g)) — the ring then circulates only the
    grouped K/V, 1/g the ICI bytes per hop of a full-head ring.
    Returns [B, Tq, H, D]. Global sequence order is block-major: device i
    of the ``axis_name`` ring holds positions [i*T, (i+1)*T).

    ``hop_attention`` selects the per-hop math: "plain" is the einsum
    online-softmax; "flash" runs the Pallas kernel per hop
    (:func:`..ops.flash_attention_lse` — fully-visible hops non-causal,
    the diagonal hop causal, future hops skipped) and merges the per-hop
    (o, lse) pairs exactly, so the sp-ring gets kernel-grade attention
    instead of materialized [Tq, Tk] score blocks; "auto" picks flash on
    TPU when the local block shape fits the kernel's tiling.
    """
    B, Tq, H, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    g = H // Hkv
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    sc = scale if scale is not None else 1.0 / math.sqrt(D)

    use_kernel = _ring_uses_kernel(Tq, Tk, hop_attention)

    # send-to-next permutation: after step i each device holds block (idx-i)%n
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_kernel:
        return _ring_flash_hops(
            q, k, v, idx=idx, n=n, perm=perm, axis_name=axis_name,
            causal=causal, sc=sc,
        )

    q_pos = idx * Tq + jnp.arange(Tq)  # global query positions
    qg = q.reshape(B, Tq, Hkv, g, D)

    # Derive the zero accumulators from q so they inherit q's shard-varying
    # axes (shard_map's VMA check requires loop-carry types to be stable).
    zero = (
        jnp.transpose(qg, (0, 2, 3, 1, 4)).astype(jnp.float32) * 0.0
    )  # [B,Hkv,g,Tq,D]
    o = zero
    m = zero[..., 0] - jnp.inf  # [B,Hkv,g,Tq] all -inf
    l = zero[..., 0]

    def accumulate(o, m, l, k, v, src):
        """Score + online-softmax update against K/V block ``src``."""

        def visible(o, m, l):
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * sc
            if causal:
                k_pos = src * Tk + jnp.arange(Tk)
                mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
                s = jnp.where(mask[None, None, None], s, -jnp.inf)
            return _online_update(o, m, l, s, v)

        if not causal:
            return visible(o, m, l)
        # Causal hop skip: block ``src`` is entirely in this shard's future
        # when its first key position exceeds the last query position — the
        # score einsum would be fully masked, pure wasted MXU work. With
        # block-major sequence order that is ~half of all (device, hop)
        # pairs at sp > 1, so the skip halves the ring's causal FLOPs.
        fully_masked = src * Tk > idx * Tq + (Tq - 1)
        return jax.lax.cond(
            fully_masked, lambda o, m, l: (o, m, l), visible, o, m, l
        )

    def body(i, carry):
        o, m, l, k, v = carry
        src = (idx - i) % n  # which global block this k/v is
        o, m, l = accumulate(o, m, l, k, v, src)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return o, m, l, k, v

    # n-1 hops rotate K/V; the final block is consumed in place (a ppermute
    # pair after the last accumulation would move data nobody reads — dead
    # ICI work). n is static (axis sizes are), so the n=1 ring traces no
    # loop and no collective at all.
    if n > 1:
        o, m, l, k, v = jax.lax.fori_loop(0, n - 1, body, (o, m, l, k, v))
    o, m, l = accumulate(o, m, l, k, v, (idx - (n - 1)) % n)
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (shouldn't occur causally)
    out = (o / l[..., None]).astype(q.dtype)  # [B,Hkv,g,Tq,D]
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(B, Tq, H, D)


def _ring_flash_hops(q, k, v, *, idx, n, perm, axis_name, causal, sc):
    """Flash-kernel ring body: per-hop (o, lse) from the Pallas kernel,
    merged exactly across hops.

    Hop classification under block-major order (Tq == Tk): ``src < idx``
    is fully visible (non-causal kernel), ``src == idx`` is the diagonal
    (causal kernel — local causality equals global there), ``src > idx``
    is fully future (skipped: zero contribution at lse=-inf). The merge
    is the associative online-softmax combine, so the result is exact.
    """
    from ..ops import flash_attention_lse

    B, Tq, H, D = q.shape

    def flash(kk, vv, hop_causal):
        o, lse = flash_attention_lse(q, kk, vv, causal=hop_causal, scale=sc)
        return o.astype(jnp.float32), lse

    def hop_result(kk, vv, src):
        """Hops 1..n-1 only (src != idx there): fully-future blocks are
        empty, the rest run the non-causal kernel. The diagonal (src ==
        idx, exactly hop 0) is peeled below so the loop body lowers one
        kernel and a two-way cond instead of three branches."""
        if not causal:
            return flash(kk, vv, False)
        empty = (
            (q * 0.0).astype(jnp.float32),
            q[..., 0].astype(jnp.float32) * 0.0 - jnp.inf,
        )
        return jax.lax.cond(
            src > idx, lambda: empty, lambda: flash(kk, vv, False)
        )

    def merge(o_a, lse_a, o_b, lse_b):
        m = jnp.maximum(lse_a, lse_b)
        m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
        wa = jnp.exp(lse_a - m_safe)
        wb = jnp.exp(lse_b - m_safe)
        denom = wa + wb
        denom_safe = jnp.where(denom == 0.0, 1.0, denom)
        o = (wa[..., None] * o_a + wb[..., None] * o_b) / denom_safe[..., None]
        lse = m_safe + jnp.log(denom_safe)
        lse = jnp.where(denom == 0.0, -jnp.inf, lse)
        return o, lse

    # Peeled hop 0: every device starts holding its own block (src ==
    # idx) — the diagonal, the only hop where local causality applies.
    o_acc, lse_acc = flash(k, v, causal)
    if n == 1:
        return o_acc.astype(q.dtype)
    k = jax.lax.ppermute(k, axis_name, perm)
    v = jax.lax.ppermute(v, axis_name, perm)

    def body(i, carry):
        o_acc, lse_acc, k, v = carry
        src = (idx - i) % n
        o_i, lse_i = hop_result(k, v, src)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_i, lse_i)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return o_acc, lse_acc, k, v

    if n > 2:
        o_acc, lse_acc, k, v = jax.lax.fori_loop(
            1, n - 1, body, (o_acc, lse_acc, k, v)
        )
    o_i, lse_i = hop_result(k, v, (idx - (n - 1)) % n)
    o_acc, _ = merge(o_acc, lse_acc, o_i, lse_i)
    return o_acc.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
    batch_axes: tuple[str, ...] | None = None,
    head_axes: str | tuple[str, ...] | None = None,
    hop_attention: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention over ``mesh``'s ``axis_name`` ring.

    Arrays are global ``[B, S, H, D]``; the sequence dim is (or will be)
    sharded over ``axis_name``, the batch dim over ``batch_axes`` and the
    heads dim over ``head_axes`` (tensor parallelism composes with the ring:
    each (tp, sp) pair works on its own head/sequence tile).
    Wraps :func:`ring_attention_block` in ``shard_map``;
    ``hop_attention`` per the block (flash-kernel hops on TPU by default
    when the local blocks fit the kernel tiling).
    """
    bspec = batch_axes if batch_axes else None
    spec = P(bspec, axis_name, head_axes, None)
    fn = functools.partial(
        ring_attention_block, axis_name=axis_name, causal=causal, scale=scale,
        hop_attention=hop_attention,
    )
    # Same gate the block consults: pallas_call outputs carry no
    # varying-mesh-axes metadata, so the VMA check must be off exactly
    # when the flash hops engage (the specs above are the full truth).
    # q and k share `spec`, so local Tq == Tk == S // n here.
    n = mesh.shape.get(axis_name, 1)
    Tq = q.shape[1] // n
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not _ring_uses_kernel(Tq, Tq, hop_attention),
    )(q, k, v)


def grouped_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Single-device grouped (GQA) attention — THE shared plain-math path.

    q: [B, S, H, D]; k, v: [B, S, Hkv, D] with H a multiple of Hkv (MHA is
    g=1). ``mask`` ([B, Tq, Tk] boolean, True = attend) composes with the
    causal mask; rows left fully masked produce zeros (never NaN). f32
    scores/softmax/accumulation, one cast at the end.

    Every consumer that needs plain grouped attention delegates here
    (``workloads.attention.grouped_full_attention``, the Ulysses inner
    fallback, padded-prefill in ``workloads.generate``) so the numerics
    exist exactly once; :func:`full_attention` stays an independent MHA
    oracle for tests.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    g = H // Hkv
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    qg = q.reshape(B, S, Hkv, g, D)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32) * sc
    m = None
    if causal:
        m = jnp.tril(jnp.ones((S, S), dtype=bool))[None]  # [1, Tq, Tk]
    if mask is not None:
        m = mask if m is None else (m & mask)
    if m is not None:
        s = jnp.where(m[:, None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        # fully-masked rows: all--inf softmax is NaN; zero them so NaN
        # never leaks into downstream residuals/caches
        dead = ~m.any(-1)  # [B|1, Tq]
        p = jnp.where(dead[:, None, None, :, None], 0.0, p)
    else:
        p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p, v).astype(q.dtype)
    return out.reshape(B, S, H, D)


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Plain single-device attention — the correctness oracle for the ring."""
    B, S, H, D = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sc
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)
    return out
