"""Device-mesh construction and sharding rules.

TPU-first design: one `jax.sharding.Mesh` with named axes

- ``dp``   — data parallelism (pure replication of params),
- ``fsdp`` — fully-sharded data parallelism (params sharded, data sharded),
- ``tp``   — tensor parallelism (matmul dims sharded; collectives ride ICI),
- ``sp``   — sequence parallelism (ring attention, ``parallel/ring.py``).

XLA inserts the collectives (psum/all-gather/reduce-scatter) from the
NamedSharding annotations; nothing here hand-schedules communication.
The plugin side of the story is only env injection (SURVEY.md section 2,
"distributed communication backend — explicitly absent" in the reference;
on TPU the mesh axes map onto the ICI torus that libtpu exposes from
``TPU_PROCESS_BOUNDS``).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Any axis may be 1 (inactive)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("dp", "fsdp", "tp", "sp")

    @classmethod
    def auto(cls, n_devices: int, *, max_tp: int = 4, want_sp: bool = False) -> "MeshSpec":
        """Factor ``n_devices`` into a sensible (dp, fsdp, tp[, sp]) shape.

        Heuristic, TPU-flavored: tp greedily takes the largest power-of-two
        factor up to ``max_tp`` (bounded so tp collectives stay
        ICI-adjacent); sp (when requested) takes a factor of 2; fsdp absorbs
        the rest; dp only appears when fsdp would exceed 8.
        """
        rem = n_devices
        tp = 1
        while tp * 2 <= max_tp and rem % 2 == 0:
            tp *= 2
            rem //= 2
        sp = 1
        if want_sp and rem % 2 == 0 and rem > 1:
            sp = 2
            rem //= 2
        fsdp, dp = rem, 1
        while fsdp > 8 and fsdp % 2 == 0:
            fsdp //= 2
            dp *= 2
        return cls(dp=dp, fsdp=fsdp, tp=tp, sp=sp)


def make_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the mesh over ``devices`` (default: all local JAX devices)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if spec is None:
        spec = MeshSpec.auto(len(devs))
    if spec.size != len(devs):
        raise ValueError(
            f"mesh spec {spec} needs {spec.size} devices, have {len(devs)}"
        )
    arr = np.array(devs).reshape(spec.dp, spec.fsdp, spec.tp, spec.sp)
    return Mesh(arr, spec.axis_names)


def batch_sharding(mesh: Mesh, *, seq_parallel: bool = False) -> NamedSharding:
    """Sharding for ``[batch, seq]`` token arrays.

    Batch shards over (dp, fsdp) — fsdp is ZeRO-style, it shards params AND
    acts as extra data parallelism; the sequence dim shards over sp when
    ring attention is in play.
    """
    return NamedSharding(
        mesh, P(("dp", "fsdp"), "sp" if seq_parallel else None)
    )


def prune_unshardable(specs, abstract, mesh: Mesh):
    """Drop sharding axes that don't divide the dimension they shard.

    The logical->physical fallback every production sharding map needs: a
    PartitionSpec tree is written for the model family (e.g. classifier
    classes over ``tp``), but a particular config (10 classes, tp=4) may
    not divide — XLA refuses such shardings outright. Any non-dividing
    axis falls back to replication for that dimension only.

    ``specs``: PartitionSpec pytree; ``abstract``: matching pytree of
    shaped leaves (e.g. from ``jax.eval_shape``).
    """
    import math

    def fix(spec, leaf):
        out = []
        for i, axis in enumerate(spec):
            if axis is None:
                out.append(None)
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            total = math.prod(mesh.shape[a] for a in axes)
            ok = i < len(leaf.shape) and leaf.shape[i] % total == 0
            out.append(axis if ok else None)
        return P(*out)

    return jax.tree.map(
        fix, specs, abstract, is_leaf=lambda x: isinstance(x, P)
    )


def commit_to_mesh(tree, mesh: Mesh):
    """Replicate onto ``mesh`` every leaf not already sharded over it.

    Optimizer moments created by ``optax.init`` inherit the params'
    NamedShardings via ``zeros_like``, but scalar counters come out pinned
    to the default device; mixing the two breaks jit (incompatible device
    sets) and checkpoint restores. This commits the stragglers as
    mesh-replicated without touching already-sharded leaves.
    """
    replicated = NamedSharding(mesh, P())

    def fix(x):
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == mesh:
            return x
        return jax.device_put(x, replicated)

    return jax.tree.map(fix, tree)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    dp_total = mesh.shape["dp"] * mesh.shape["fsdp"]
    if global_batch % dp_total:
        raise ValueError(f"global batch {global_batch} not divisible by dp*fsdp={dp_total}")
    return global_batch // dp_total
