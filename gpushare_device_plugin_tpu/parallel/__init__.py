"""Pod-side JAX parallel runtime.

This is the *workload* half of the framework: the device plugin injects
topology env vars at container admission (``TPU_VISIBLE_CHIPS``,
``TPU_PROCESS_BOUNDS``, ``ALIYUN_COM_TPU_MEM_*`` — the TPU analog of the
reference's ``NVIDIA_VISIBLE_DEVICES`` injection, ``allocate.go:109-124``),
and this package consumes them: cooperative HBM capping, mesh construction
over the granted chips, sharding rules, and ring attention for
sequence-parallel long-context work.

The reference has no workload-side runtime at all (SURVEY.md section 2,
"parallelism strategies — explicitly absent"); this package is the
TPU-native completion of the story: a pod that was binpacked onto a
fractional HBM slice needs to (a) self-limit its XLA client allocation and
(b) build its `jax.sharding.Mesh` from what the plugin granted.
"""

from .podenv import (  # noqa: F401
    MultihostSpec,
    PodTpuEnv,
    configure_jax_from_env,
    gang_mesh,
    gang_mesh_spec,
    initialize_multihost,
    multihost_spec,
)
from .mesh import MeshSpec, make_mesh, batch_sharding  # noqa: F401
from .ring import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
