"""Consume plugin-injected env inside a workload pod.

The Allocate() hot path (``allocator/env.py``; reference ``allocate.go:109-124``)
injects:

- ``TPU_VISIBLE_CHIPS`` — comma-separated local chip indices granted to the
  container (analog of ``NVIDIA_VISIBLE_DEVICES``),
- ``TPU_PROCESS_BOUNDS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` — multi-host
  slice topology strings libtpu uses to form the global mesh,
- ``ALIYUN_COM_TPU_MEM_{IDX,POD,CONTAINER,DEV}`` — the HBM-unit accounting
  annotations mirrored into env,
- ``TPU_HBM_LIMIT_FRACTION`` — cooperative HBM cap (there is no hardware
  fence for fractional HBM, same as GPU memory in the reference; the cGPU
  analog toggle is the ``ctpu.disable.isolation`` node label,
  ``podmanager.go:59-72``).

``configure_jax_from_env()`` translates these into the env vars the JAX/XLA
TPU client actually reads and must run **before** ``import jax`` initializes
a backend.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping

from .. import const


def _env_int(e: Mapping[str, str], key: str, default: int) -> int:
    try:
        return int(e.get(key, ""))
    except ValueError:
        return default


def _env_str(e: Mapping[str, str], key: str, default: str = "") -> str:
    """One canonical string read: absent, None-ish, and whitespace-only
    all normalize to ``default`` — every annotation-mirrored var parses
    through here so a new field cannot drift from the gang/class/mem
    precedents by hand-rolling its own ``e.get`` dance."""
    val = str(e.get(key, "") or "").strip()
    return val if val else default


def _env_choice(
    e: Mapping[str, str], key: str, choices: tuple[str, ...], default: str
) -> str:
    """:func:`_env_str` constrained to an enumerated wire value; anything
    off-list normalizes to ``default`` (for the workload class that is
    the protect-never-throttle rule: unknown -> latency-critical)."""
    val = _env_str(e, key)
    return val if val in choices else default


@dataclasses.dataclass(frozen=True)
class PodTpuEnv:
    """Parsed view of the plugin-injected container env."""

    visible_chips: tuple[int, ...]  # local chip indices granted
    chip_index: int  # primary assigned chip (MEM_IDX), -1 if unset
    mem_units_container: int  # this container's HBM units
    mem_units_chip: int  # total units on the assigned chip
    process_bounds: str  # "" on single-host
    chips_per_process_bounds: str
    hbm_fraction: float  # cooperative cap in (0, 1]
    # Multi-chip gang grant (ALIYUN_COM_TPU_GANG_*): the member chips,
    # the granted slice shape, and the HBM units claimed on EACH member.
    # Empty/0 for ordinary single-chip pods.
    gang_chips: tuple[int, ...] = ()
    gang_shape: tuple[int, ...] = ()
    gang_per_chip: int = 0
    mem_units_pod: int = 0  # the whole pod's HBM units (MEM_POD), 0 unset
    # QoS class the admission PATCH normalized and mirrored into the env
    # (ALIYUN_COM_TPU_WORKLOAD_CLASS): latency-critical | best-effort.
    # The serving side attaches a step governor to best-effort engines.
    workload_class: str = const.WORKLOAD_LATENCY_CRITICAL
    # Per-tenant LoRA adapter id mirrored from the pod's
    # tpushare.aliyun.com/lora-adapter annotation
    # (ALIYUN_COM_TPU_LORA_ADAPTER): the fine-tune this pod's requests
    # decode through by default; "" = the base model. The serving engine
    # validates the id against its lora_store and prefetches the
    # adapter's paged slab load at startup.
    lora_adapter: str = ""

    @property
    def is_best_effort(self) -> bool:
        return self.workload_class == const.WORKLOAD_BEST_EFFORT

    @property
    def exclusive(self) -> bool:
        """Whole chip(s) granted — no HBM cap needed."""
        return self.hbm_fraction >= 0.999

    @property
    def is_gang(self) -> bool:
        """A topology-aware multi-chip grant: the workload should build a
        tensor-parallel mesh over its visible chips
        (:func:`gang_mesh_spec`)."""
        return len(self.gang_chips) > 1

    def mem_bytes(self, unit: "const.MemoryUnit | None" = None) -> int:
        """This container's ``aliyun.com/tpu-mem`` slice in bytes (units
        are GiB unless the cluster runs ``--memory-unit=MiB``). For a
        gang this is the TOTAL across member chips; the per-chip share is
        :meth:`gang_per_chip_bytes`. The serving engine sizes its KV slot
        pool from these (``serving.engine.slots_from_pod_env``)."""
        u = unit if unit is not None else const.MemoryUnit.GiB
        return self.mem_units_container * u.num_bytes

    def gang_per_chip_bytes(self, unit: "const.MemoryUnit | None" = None) -> int:
        """The HBM slice this gang holds on EACH member chip, in bytes
        (0 for non-gang pods). POD-level: in a multi-container gang pod
        this is the whole pod's per-chip share; THIS container's portion
        is :meth:`gang_container_per_chip_bytes`."""
        u = unit if unit is not None else const.MemoryUnit.GiB
        return self.gang_per_chip * u.num_bytes

    def gang_container_per_chip_bytes(
        self, unit: "const.MemoryUnit | None" = None
    ) -> int:
        """This CONTAINER's per-chip share of the gang's slice: the pod
        per-chip share scaled by the container's fraction of the pod's
        units. Two serving containers in one gang pod must each size to
        their own portion — sizing both to the pod share would pin ~2x
        the granted per-chip HBM."""
        per = self.gang_per_chip_bytes(unit)
        if 0 < self.mem_units_container < self.mem_units_pod:
            return per * self.mem_units_container // self.mem_units_pod
        return per

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "PodTpuEnv":
        e = os.environ if env is None else env

        def _int(key: str, default: int) -> int:
            return _env_int(e, key, default)

        def _int_list(key: str) -> tuple[int, ...]:
            raw = e.get(key, "")
            return tuple(
                int(tok) for tok in raw.split(",") if tok.strip().isdigit()
            )

        visible = _int_list(const.ENV_TPU_VISIBLE_CHIPS)
        container_units = _int(const.ENV_MEM_CONTAINER, 0)
        chip_units = _int(const.ENV_MEM_DEV, 0)
        gang_chips = _int_list(const.ENV_GANG_CHIPS)
        gang_per_chip = _int(const.ENV_GANG_PER_CHIP, 0)
        gang_shape: tuple[int, ...] = ()
        shape_raw = _env_str(e, const.ENV_GANG_SHAPE)
        if shape_raw:
            from ..topology import parse_shape

            try:
                # the one wire-format parser: rejects non-positive dims
                # and >3 axes the same way every control-plane consumer does
                gang_shape = parse_shape(shape_raw)
            except ValueError:
                gang_shape = ()
        explicit = None
        frac_raw = e.get(const.ENV_XLA_MEM_FRACTION, "")
        if frac_raw:
            try:
                explicit = min(1.0, max(0.0, float(frac_raw)))
            except ValueError:
                explicit = None
        if gang_chips and gang_per_chip > 0 and chip_units > 0:
            # Gang pods cap PER CHIP: each member chip holds gang_per_chip
            # of its chip_units (the container total spans every member).
            derived = min(1.0, gang_per_chip / chip_units)
            fraction = min(explicit, derived) if explicit is not None else derived
        elif container_units > 0 and chip_units > 0:
            derived = min(1.0, container_units / chip_units)
            # The container never gets more than its own units' fraction,
            # whatever the explicit env says (defense against a stale or
            # pod-level value in a multi-container pod).
            fraction = min(explicit, derived) if explicit is not None else derived
        else:
            fraction = explicit if explicit is not None else 1.0
        return cls(
            visible_chips=visible,
            chip_index=_int(const.ENV_MEM_IDX, -1),
            mem_units_container=container_units,
            mem_units_chip=chip_units,
            process_bounds=_env_str(e, const.ENV_TPU_PROCESS_BOUNDS),
            chips_per_process_bounds=_env_str(
                e, const.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS
            ),
            hbm_fraction=fraction,
            gang_chips=gang_chips,
            gang_shape=gang_shape,
            gang_per_chip=gang_per_chip,
            mem_units_pod=_int(const.ENV_MEM_POD, 0),
            workload_class=_env_choice(
                e, const.ENV_WORKLOAD_CLASS, const.WORKLOAD_CLASSES,
                const.WORKLOAD_LATENCY_CRITICAL,
            ),
            lora_adapter=_env_str(e, const.ENV_LORA_ADAPTER),
        )


def configure_jax_from_env(
    env: Mapping[str, str] | None = None,
    *,
    headroom: float = 0.95,
) -> dict[str, str]:
    """Compute the JAX/XLA client settings from the injected env.

    With ``env=None`` (the in-pod case) the settings are also applied to
    ``os.environ``; with an explicit mapping the call is pure — inspection
    and tests don't pollute the process environment.

    ``headroom`` shaves the cooperative cap so two co-scheduled pods whose
    fractions sum to 1.0 don't collide on allocator slack — the fractional
    sharing here is cooperative, exactly like the reference's GPU memory
    sharing (no hardware fence; SURVEY.md section 7 "hard parts" (d)).
    """
    apply = env is None
    pod = PodTpuEnv.from_env(env)
    settings: dict[str, str] = {}
    if not pod.exclusive:
        settings[const.ENV_XLA_PYTHON_MEM_FRACTION] = f"{pod.hbm_fraction * headroom:.3f}"
        # Pre-allocating the full fraction up-front keeps co-tenants honest:
        # a pod that exceeds its slice OOMs itself, not its neighbor.
        settings[const.ENV_XLA_PYTHON_PREALLOCATE] = "true"
    if pod.process_bounds:
        settings[const.ENV_TPU_PROCESS_BOUNDS] = pod.process_bounds
    if pod.chips_per_process_bounds:
        settings[const.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] = pod.chips_per_process_bounds
    if pod.visible_chips:
        settings[const.ENV_TPU_VISIBLE_CHIPS] = ",".join(
            str(i) for i in pod.visible_chips
        )
    if apply:
        for k, v in settings.items():
            os.environ[k] = v
    return settings


def gang_mesh_spec(pod: "PodTpuEnv | None" = None, env: Mapping[str, str] | None = None):
    """The logical mesh a granted gang materializes as: pure tensor
    parallelism over the member chips (``MeshSpec(tp=n)``) — the serving
    default, where the model and the slot-pool KV cache shard across the
    gang and every collective stays inside the granted ICI sub-slice.
    Training workloads that want dp/fsdp instead can factor the same chip
    count through ``MeshSpec.auto``. Returns None for non-gang pods."""
    from .mesh import MeshSpec

    p = pod if pod is not None else PodTpuEnv.from_env(env)
    if not p.is_gang:
        return None
    return MeshSpec(tp=len(p.gang_chips))


def gang_mesh(
    pod: "PodTpuEnv | None" = None,
    env: Mapping[str, str] | None = None,
    devices=None,
):
    """Build the gang's ``jax.sharding.Mesh`` over the local devices the
    grant exposes. Call after :func:`configure_jax_from_env` (so the
    process only sees its gang's chips); ``devices`` overrides for tests.
    Returns None for non-gang pods; raises when the visible device count
    does not match the granted gang size (a mis-injected env must fail
    loudly at startup, not shard onto a neighbor's chip)."""
    p = pod if pod is not None else PodTpuEnv.from_env(env)
    spec = gang_mesh_spec(p)
    if spec is None:
        return None
    import jax

    from .mesh import make_mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) != spec.size:
        # Either direction is a mis-injected env: fewer devices cannot
        # form the mesh, and MORE means chips outside the grant leaked
        # into the container — silently meshing over the first N would
        # shard onto devices this pod was never granted.
        raise ValueError(
            f"gang grant spans {spec.size} chips but {len(devs)} devices "
            "are visible — TPU_VISIBLE_CHIPS and the gang annotations "
            "disagree"
        )
    return make_mesh(spec, devices=devs)


@dataclasses.dataclass(frozen=True)
class MultihostSpec:
    """Parsed multi-host bootstrap env (BASELINE cfg 4, one pod per host)."""

    coordinator_address: str
    num_processes: int
    process_id: int

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1 and bool(self.coordinator_address)


def _ordinal_from_hostname(hostname: str) -> int | None:
    """StatefulSet pods are named ``<name>-<ordinal>`` — a stable process id."""
    _, _, tail = hostname.rpartition("-")
    return int(tail) if tail.isdigit() else None


def multihost_spec(env: Mapping[str, str] | None = None) -> MultihostSpec:
    """Read the multi-host bootstrap contract from the container env.

    ``TPUSHARE_PROCESS_ID`` defaults to the StatefulSet ordinal parsed from
    the hostname, so the v4-32 demo (``demo/flagship/``) needs no per-pod
    env stanzas: a headless Service gives pod 0 a stable DNS name for the
    coordinator and ordinals give process ids. A multi-host spec with an
    undeterminable or out-of-range process id raises rather than letting
    every pod silently claim process 0 (which would hang the rendezvous).
    """
    e = os.environ if env is None else env
    coordinator = e.get(const.ENV_COORDINATOR_ADDRESS, "")
    num = _env_int(e, const.ENV_NUM_PROCESSES, 1)
    pid = _env_int(e, const.ENV_PROCESS_ID, -1)
    if pid < 0:
        ordinal = _ordinal_from_hostname(e.get("HOSTNAME", ""))
        if ordinal is None:
            if num > 1 and coordinator:
                raise ValueError(
                    f"multi-host spec ({const.ENV_NUM_PROCESSES}={num}) but "
                    f"no {const.ENV_PROCESS_ID} and hostname "
                    f"{e.get('HOSTNAME', '')!r} has no StatefulSet ordinal "
                    "suffix — cannot determine this pod's process id"
                )
            ordinal = 0
        pid = ordinal
    if num > 1 and coordinator and pid >= num:
        raise ValueError(
            f"process id {pid} out of range for {const.ENV_NUM_PROCESSES}={num} "
            "(pod name ordinal and the StatefulSet replica count disagree?)"
        )
    return MultihostSpec(
        coordinator_address=coordinator, num_processes=num, process_id=pid
    )


def initialize_multihost(env: Mapping[str, str] | None = None) -> MultihostSpec:
    """``jax.distributed.initialize`` from the injected env (no-op single-host).

    Call once, after :func:`configure_jax_from_env` and before any other JAX
    use. On an ``n``-host slice every host's JAX process then sees all
    ``n x chips`` devices and ``make_mesh`` builds the global mesh; XLA
    routes mesh-axis collectives over ICI within a host/slice and DCN
    across (the scaling-book recipe — the plugin's role ends at env
    injection, SURVEY.md section 5 "distributed communication backend").
    """
    spec = multihost_spec(env)
    if spec.is_multihost:
        import jax

        jax.distributed.initialize(
            coordinator_address=spec.coordinator_address,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
        )
    return spec
