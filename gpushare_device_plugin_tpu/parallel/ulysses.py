"""Ulysses-style all-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

The second context-parallel strategy next to the ring (``ring.py``), with
the opposite trade:

- **Ring**: K/V circulate over ``sp`` (n-1 ppermute hops, each 1/n of the
  K/V bytes); per-hop attention is the flash kernel when the local block
  fits (``ring.py`` merges per-hop (o, lse) pairs), else plain einsum.
- **Ulysses**: TWO ``all_to_all`` collectives swap the sharding from
  sequence to heads and back; between them every device holds the FULL
  sequence for H/n heads, so the inner attention runs once, whole-S,
  through any implementation — including the flash kernel.

Which wins is shape-dependent: Ulysses moves O(S·H·D/n) bytes twice per
layer and runs one whole-sequence kernel; the ring overlaps its hop
transfers with compute and runs a kernel per hop. Both are exact. On TPU
both map to ICI collectives XLA schedules asynchronously.

Constraint: the ``sp`` axis size must divide the head count (heads are
scattered over it). GQA: grouped K/V with ``Hkv % n == 0`` scatters
natively (1/g the bytes); otherwise K/V heads are block-replicated only
``n/gcd(Hkv, n)``-fold — a scatter over the gcd with an in-group
broadcast — which keeps the grouped layout on the wire instead of
repeating up to the full query head count (Llama-3-8B has Hkv=8: at
sp=16 the wire cost is 2x grouped, not g=4x).
"""

from __future__ import annotations

import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .ring import grouped_attention


def ulysses_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
    attn_fn: Callable | None = None,
) -> jax.Array:
    """Per-shard Ulysses body — call *inside* ``shard_map``.

    q: [B, S/n, H, D]; k, v: [B, S/n, Hkv, D] (grouped OK). Returns
    [B, S/n, H, D]. ``attn_fn(q, k, v, causal=..., scale=...)`` runs on the
    head-sharded/full-sequence layout — defaults to plain grouped
    attention; pass the flash kernel for the TPU fast path.
    """
    n = jax.lax.psum(1, axis_name)
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    g = H // Hkv if Hkv else 0
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    if H % n:
        raise ValueError(f"Ulysses needs heads {H} divisible by sp={n}")

    def seq_to_heads(x):  # [B, S/n, h, D] -> [B, S, h/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    q = seq_to_heads(q)
    if Hkv % n == 0:
        k = seq_to_heads(k)
        v = seq_to_heads(v)
    else:
        # Too few KV heads to scatter 1:1. Scatter over d = gcd(Hkv, n) and
        # broadcast within each group of r = n/d devices: block-replicate
        # the d head-blocks r-fold (r <= g wire bytes, never the g-fold of
        # repeating to full query heads), all-to-all, then gather each
        # device's exact heads out of its received block. Head alignment
        # (every local q-group maps to one received head) is guaranteed by
        # H % Hkv == 0 and H % n == 0: (n/d) | g, so the local group size
        # g*d/n is a positive integer.
        d = math.gcd(Hkv, n)
        r = n // d
        hb = Hkv // d  # heads per block = kv head slots per device
        g_local = (H // n) // hb  # local q heads served per kv slot

        def scatter_grouped(x):
            xb = x.reshape(B, T, d, hb, D)
            xb = jnp.repeat(xb, r, axis=2)  # block-replicate, not per-head
            return seq_to_heads(xb.reshape(B, T, n * hb, D))

        k = scatter_grouped(k)  # [B, S, hb, D] — block j//r of kv heads
        v = scatter_grouped(v)
        # Device j's q heads [j*H/n, (j+1)*H/n) need global kv heads
        # (j*H/n + t*g_local)//g; re-index them out of the received block
        # (offset a*hb, a = j//r) into standard grouped order.
        j = jax.lax.axis_index(axis_name)
        t = jnp.arange(hb)
        local_idx = (j * (H // n) + t * g_local) // g - (j // r) * hb
        k = jnp.take(k, local_idx, axis=2)
        v = jnp.take(v, local_idx, axis=2)
    fn = attn_fn if attn_fn is not None else grouped_attention
    out = fn(q, k, v, causal=causal, scale=scale)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
    batch_axes: tuple[str, ...] | None = None,
    head_axes: str | tuple[str, ...] | None = None,
    attn_fn: Callable | None = None,
) -> jax.Array:
    """Sequence-parallel attention via all-to-all over ``axis_name``.

    Same global-array signature and sharding contract as
    :func:`..ring.ring_attention` (sequence over ``axis_name``, batch over
    ``batch_axes``, heads over ``head_axes``) — the two schemes are
    drop-in interchangeable; ``TransformerConfig.context_parallel``
    selects per model.
    """
    bspec = batch_axes if batch_axes else None
    spec = P(bspec, axis_name, head_axes, None)
    fn = functools.partial(
        ulysses_attention_block,
        axis_name=axis_name, causal=causal, scale=scale, attn_fn=attn_fn,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axes metadata (same
        # limitation flash_or_plain works around): with the flash kernel
        # as attn_fn, the VMA check would reject the kernel output feeding
        # all_to_all. The specs above are the full truth here.
        check_vma=False,
    )(q, k, v)
