"""Weight-only int8 quantization for serving the decoder.

The point, in this framework's terms: pods are binpacked onto *fractional
HBM slices* (the plugin's whole reason to exist), and weight-only int8
cuts the decoder's parameter HBM by ~4x — a model that needed a 16 GiB
slice serves from 4-and-change, or a 2 GiB slice hosts 4x the parameters.
On TPU the dequantize (int8 -> bf16 multiply by a per-channel scale)
fuses into the consuming matmul's operand read under XLA, so the storage
saving does not cost a materialized full-precision copy per step.

Scheme: symmetric per-output-channel int8 (`q8 = round(w / scale)`,
`scale = max|w| / 127` reduced over the matmul *contraction* axes, kept
as broadcastable keepdims). Norm gains stay f32 (tiny, precision-
critical); activations stay in ``cfg.compute_dtype`` — this is weight-only
quantization, the standard serving recipe.

Integration: :func:`quantize_decoder` maps a trained param tree to a
quantized one; ``generate.prefill``/``decode_step`` accept either tree —
quantized layer weights are dequantized per layer *inside* the scan body,
so only one layer's full-precision weights exist at a time.

The same recipe extends to the KV cache (:func:`quantize_kv` /
:func:`dequantize_kv`, ``generate.init_cache(kv_dtype="int8")``):
per-(token, head) symmetric int8 halves the cache stream that floors
long-context decode latency, and composes with weight-only int8 for the
fully quantized serving stack.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# Contraction axes per stacked layer weight (axis 0 is the scan's L dim):
# reducing max|w| over them yields one scale per output channel.
_LAYER_AXES = {
    "wq": (1,),      # [L, d, H, Dh] contracts d
    "wkv": (1,),     # [L, d, 2, Hkv, Dh] contracts d
    "wo": (1, 2),    # [L, H, Dh, d] contracts (H, Dh)
    "wi": (1,),      # [L, d, 2, F] contracts d
    "wdown": (1,),   # [L, F, d] contracts F
}
_KEEP_FP = ("ln1", "ln2")


def quantize(w: jax.Array, axes: tuple[int, ...]) -> Params:
    """Symmetric int8 with per-channel scale over ``axes`` (keepdims)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q8 = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale.astype(jnp.float32)}


def is_qtensor(x: Any) -> bool:
    return isinstance(x, dict) and set(x) == {"q8", "scale"}


def dequantize(qt: Params, dtype=jnp.float32) -> jax.Array:
    return (qt["q8"].astype(jnp.float32) * qt["scale"]).astype(dtype)


def quantize_decoder(params: Params) -> Params:
    """Quantize a trained decoder tree (``transformer.init_params`` layout).

    Layer matmul weights and the embed/out projections go int8; norm gains
    stay f32. The result is a drop-in ``params`` argument for
    ``generate.generate``/``prefill``/``decode_step``.
    """
    layers = {}
    for name, w in params["layers"].items():
        if name in _KEEP_FP:
            layers[name] = w
        else:
            layers[name] = quantize(w, _LAYER_AXES[name])
    return {
        # embed is a gather: per-ROW scale so a token's row dequantizes
        # from its own scale ([V, d] reduced over d)
        "embed": quantize(params["embed"], (1,)),
        "layers": layers,
        "final_norm": params["final_norm"],
        # out projection [d, V] contracts d
        "out": quantize(params["out"], (0,)),
    }


def cast_decoder(params: Params, dtype=jnp.bfloat16) -> Params:
    """Serving-precision copy of a trained decoder tree: matmul weights and
    embeddings cast to ``dtype`` (bf16 halves parameter HBM vs the f32
    master copy), norm gains kept f32 — the bf16 counterpart of
    :func:`quantize_decoder`, and the honest baseline to compare it
    against (a serving stack never streams f32 masters)."""
    layers = {
        name: (w if name in _KEEP_FP else w.astype(dtype))
        for name, w in params["layers"].items()
    }
    return {
        "embed": params["embed"].astype(dtype),
        "layers": layers,
        "final_norm": params["final_norm"],
        "out": params["out"].astype(dtype),
    }


def dequantize_tree(tree: Any, dtype=jnp.float32) -> Any:
    """Recursively replace qtensors with full-precision arrays."""
    if is_qtensor(tree):
        return dequantize(tree, dtype)
    if isinstance(tree, dict):
        return {k: dequantize_tree(v, dtype) for k, v in tree.items()}
    return tree


def param_bytes(tree: Any) -> int:
    """Total bytes of array leaves (quantized trees count q8 + scales)."""
    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "dtype")
    )


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the head_dim axis (last): per-(…, token, head)
    scale. x: [..., Dh] -> (q8 [..., Dh] int8, scale [...] f32).

    The KV-cache analog of the weight scheme — same recipe as
    :func:`quantize` (one implementation of the scale/clip math), tuple
    layout instead of a qtensor dict because the cache stores q8 and
    scales as separate scan-carried arrays. Per-token-head scales keep
    the error at int8 resolution regardless of outliers elsewhere.
    """
    qt = quantize(x, (-1,))
    return qt["q8"], qt["scale"][..., 0]


def dequantize_kv(q8: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`quantize_kv`; under jit the multiply fuses into
    the consuming attention einsum, so HBM holds only int8 + scales."""
    return (q8.astype(jnp.float32) * scale[..., None]).astype(dtype)


def embed_lookup(embed: Any, tokens: jax.Array, dtype) -> jax.Array:
    """Embedding gather for fp or quantized tables.

    Quantized: gather int8 rows + their scales, THEN dequantize — the full
    table is never materialized in fp.
    """
    if is_qtensor(embed):
        rows = embed["q8"][tokens].astype(jnp.float32)
        scales = embed["scale"][tokens]
        return (rows * scales).astype(dtype)
    return embed.astype(dtype)[tokens]


def matmul_weight(w: Any, dtype) -> jax.Array:
    """Materialize a (possibly quantized) matmul operand in compute dtype.

    Under jit the dequantize fuses into the consuming matmul; HBM holds
    only the int8 copy.
    """
    if is_qtensor(w):
        return dequantize(w, dtype)
    return w.astype(dtype)
