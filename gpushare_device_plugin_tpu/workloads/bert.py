"""BERT-style bidirectional encoder with an MLM head (BASELINE.md config 3).

Design notes (TPU/XLA):
- **scan over layers** — identical to the decoder flagship
  (``workloads/transformer.py``): stacked layer params under one compiled
  `lax.scan` body.
- **non-causal flash attention** — reuses the Pallas kernel
  (``ops/flash_attention.py``) with ``causal=False`` on TPU; plain softmax
  attention elsewhere.
- **bf16 compute / f32 params**, MLM head tied to the token embedding
  (the classic BERT weight tying — one big [d, vocab] matmul on the MXU).
- **sharding** — same (dp, fsdp, tp, sp) mesh rules as the decoder: fsdp
  ZeRO-shards the model dim, tp shards heads/ffn/vocab, batch shards over
  (dp, fsdp).

The reference has no model code (SURVEY.md section 2); this is the second
workload of the two-pods-on-one-host demo (BASELINE.md config 3: ResNet-50
+ BERT-base HBM-binpacked onto one v4-8 host).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import batch_sharding, commit_to_mesh, prune_unshardable
from .attention import flash_or_plain

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_seq: int = 512
    n_segments: int = 2
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    attention: str = "auto"  # auto | flash | plain
    mask_token_id: int = 1  # [MASK] for demo MLM batches

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_base(vocab: int = 30522) -> BertConfig:
    """The BERT-base (L=12, H=768, A=12) shape."""
    return BertConfig(
        vocab=vocab, d_model=768, n_layers=12, n_heads=12, d_ff=3072, max_seq=512
    )


# --- init -------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: BertConfig) -> Params:
    k_tok, k_pos, k_seg, k_layers = jax.random.split(rng, 4)
    d, H, Dh, F, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape) / jnp.sqrt(fan_in)).astype(jnp.float32)

    ks = jax.random.split(k_layers, 4)
    return {
        "embed": norm(k_tok, (cfg.vocab, d), d),
        "pos_embed": norm(k_pos, (cfg.max_seq, d), d),
        "seg_embed": norm(k_seg, (cfg.n_segments, d), d),
        "embed_ln": {"scale": jnp.ones((d,), jnp.float32),
                     "bias": jnp.zeros((d,), jnp.float32)},
        "layers": {
            "wqkv": norm(ks[0], (L, d, 3, H, Dh), d),
            "wo": norm(ks[1], (L, H, Dh, d), d),
            "wi": norm(ks[2], (L, d, F), d),
            "wdown": norm(ks[3], (L, F, d), F),
            "ln1": {"scale": jnp.ones((L, d), jnp.float32),
                    "bias": jnp.zeros((L, d), jnp.float32)},
            "ln2": {"scale": jnp.ones((L, d), jnp.float32),
                    "bias": jnp.zeros((L, d), jnp.float32)},
        },
        # MLM head: dense + layernorm, output projection tied to `embed`.
        "mlm": {
            "dense": norm(jax.random.fold_in(rng, 7), (d, d), d),
            "ln": {"scale": jnp.ones((d,), jnp.float32),
                   "bias": jnp.zeros((d,), jnp.float32)},
            "out_bias": jnp.zeros((cfg.vocab,), jnp.float32),
        },
    }


def param_specs(cfg: BertConfig) -> Params:
    ln = {"scale": P(None), "bias": P(None)}
    layer_ln = {"scale": P(None, None), "bias": P(None, None)}
    return {
        "embed": P("tp", "fsdp"),
        "pos_embed": P(None, "fsdp"),
        "seg_embed": P(None, "fsdp"),
        "embed_ln": ln,
        "layers": {
            "wqkv": P(None, "fsdp", None, "tp", None),
            "wo": P(None, "tp", None, "fsdp"),
            "wi": P(None, "fsdp", "tp"),
            "wdown": P(None, "tp", "fsdp"),
            "ln1": layer_ln,
            "ln2": layer_ln,
        },
        "mlm": {"dense": P("fsdp", "tp"), "ln": ln, "out_bias": P("tp")},
    }


def param_shardings(mesh: Mesh, cfg: BertConfig) -> Params:
    abstract = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    specs = prune_unshardable(param_specs(cfg), abstract, mesh)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, mesh: Mesh, cfg: BertConfig) -> Params:
    return jax.device_put(params, param_shardings(mesh, cfg))


# --- model ------------------------------------------------------------------


def _layer_norm(x, p, eps=1e-6):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _layer(x, lp, cfg: BertConfig, mesh: Mesh | None):
    """One post-LN encoder block. x: [B, T, d]."""
    dt = cfg.compute_dtype
    qkv = jnp.einsum("btd,dchn->btchn", x, lp["wqkv"].astype(dt))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = flash_or_plain(
        q, k, v, attention=cfg.attention, causal=False, mesh=mesh
    )
    x = _layer_norm(
        x + jnp.einsum("bthn,hnd->btd", attn, lp["wo"].astype(dt)), lp["ln1"]
    )
    ff = jax.nn.gelu(jnp.einsum("btd,df->btf", x, lp["wi"].astype(dt)))
    x = _layer_norm(
        x + jnp.einsum("btf,fd->btd", ff, lp["wdown"].astype(dt)), lp["ln2"]
    )
    return x


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: BertConfig,
    mesh: Mesh | None = None,
    segments: jax.Array | None = None,
) -> jax.Array:
    """tokens: [B, S] int32 -> contextual embeddings [B, S, d]."""
    dt = cfg.compute_dtype
    B, S = tokens.shape
    x = params["embed"].astype(dt)[tokens]
    x = x + params["pos_embed"].astype(dt)[:S][None]
    if segments is not None:
        x = x + params["seg_embed"].astype(dt)[segments]
    x = _layer_norm(x, params["embed_ln"])
    layer_fn = functools.partial(_layer, cfg=cfg, mesh=mesh)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    x = jax.lax.scan(lambda c, lp: (layer_fn(c, lp), None), x, params["layers"])[0]
    return x


def mlm_logits(params: Params, hidden: jax.Array, cfg: BertConfig) -> jax.Array:
    """[B, S, d] -> [B, S, vocab] via the tied-embedding MLM head."""
    dt = cfg.compute_dtype
    h = jax.nn.gelu(jnp.einsum("btd,de->bte", hidden, params["mlm"]["dense"].astype(dt)))
    h = _layer_norm(h, params["mlm"]["ln"])
    logits = jnp.einsum("btd,vd->btv", h, params["embed"].astype(dt))
    return logits.astype(jnp.float32) + params["mlm"]["out_bias"]


def loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    mask: jax.Array,
    cfg: BertConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Masked-LM cross-entropy over positions where ``mask`` is 1."""
    hidden = forward(params, tokens, cfg, mesh)
    logits = mlm_logits(params, hidden, cfg)
    lp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll * mask) / denom


# --- training ---------------------------------------------------------------


def make_optimizer(lr: float = 1e-4, **kw) -> optax.GradientTransformation:
    """AdamW + clip (+ warmup-cosine with total_steps=...); see optim.py."""
    from .optim import make_optimizer as _mk

    return _mk(lr, **kw)


def make_train_step(mesh: Mesh, cfg: BertConfig, optimizer=None):
    """(params, opt_state, tokens, targets, mask) -> (params, opt_state, loss)."""
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    data_sh = batch_sharding(mesh)

    def step(params, opt_state, tokens, targets, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets, mask, cfg, mesh)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(psh, None, data_sh, data_sh, data_sh),
        out_shardings=(psh, None, None),
        donate_argnums=(0, 1),
    )


def init_train_state(rng: jax.Array, mesh: Mesh, cfg: BertConfig, optimizer=None):
    """Init under jit with ``out_shardings``: weights are created in-shard
    (see transformer.init_train_state for why)."""
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    params = jax.jit(lambda k: init_params(k, cfg), out_shardings=psh)(rng)
    opt_state = commit_to_mesh(opt.init(params), mesh)  # see transformer
    return params, opt_state


def demo_batch(rng: jax.Array, batch: int, seq: int, cfg: BertConfig):
    """Synthetic MLM batch: (tokens, targets, mask), 15% positions masked."""
    k_tok, k_mask = jax.random.split(rng)
    base = jax.random.randint(k_tok, (batch, 1), 2, cfg.vocab // 2)
    ramp = jnp.arange(seq)[None, :]
    targets = ((base + ramp) % (cfg.vocab - 2) + 2).astype(jnp.int32)
    mask = (jax.random.uniform(k_mask, (batch, seq)) < 0.15).astype(jnp.float32)
    tokens = jnp.where(mask == 1.0, cfg.mask_token_id, targets).astype(jnp.int32)
    return tokens, targets, mask
