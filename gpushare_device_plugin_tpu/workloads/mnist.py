"""MNIST-scale MLP demo workload (BASELINE.md config 2).

The "one JAX MNIST pod requesting 4 GiB tpu-mem" scenario: a small
classifier whose training step data-parallelizes over whatever chips the
plugin granted (``parallel.podenv`` + a (dp,) mesh). Data is synthetic
(zero-egress image — no dataset downloads): class-conditional Gaussian
blobs, which the MLP must separate, so the loss curve is a real training
signal for e2e smoke tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

IMAGE_DIM = 784
N_CLASSES = 10


def init_params(rng: jax.Array, hidden: int = 128):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (IMAGE_DIM, hidden)) / jnp.sqrt(IMAGE_DIM),
        "b1": jnp.zeros((hidden,)),
        "w2": jax.random.normal(k2, (hidden, N_CLASSES)) / jnp.sqrt(hidden),
        "b2": jnp.zeros((N_CLASSES,)),
    }


def forward(params, images):
    h = jax.nn.relu(images @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, images, labels):
    logits = forward(params, images)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    )


def make_train_step(mesh: Mesh | None = None, lr: float = 1e-2):
    """Jitted (params, opt_state, images, labels) -> (params, opt_state, loss).

    With a mesh, params replicate and the batch shards over every mesh axis
    (pure DP — the right parallelism at this model scale).
    """
    opt = optax.sgd(lr, momentum=0.9)

    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step), opt
    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(tuple(mesh.axis_names)))
    return (
        jax.jit(
            step,
            in_shardings=(rep, None, data, data),
            out_shardings=(rep, None, None),
            donate_argnums=(0, 1),
        ),
        opt,
    )


def synthetic_batch(rng: jax.Array, batch: int):
    """Class-conditional Gaussian blobs in pixel space.

    The class prototypes come from a fixed key so every batch samples the
    *same* 10-class problem — fresh per-step rngs stay learnable.
    """
    k_label, k_noise = jax.random.split(rng)
    labels = jax.random.randint(k_label, (batch,), 0, N_CLASSES)
    protos = jax.random.normal(jax.random.key(42), (N_CLASSES, IMAGE_DIM))
    images = protos[labels] + 0.3 * jax.random.normal(k_noise, (batch, IMAGE_DIM))
    return images.astype(jnp.float32), labels.astype(jnp.int32)


def train(steps: int = 50, batch: int = 256, mesh: Mesh | None = None, seed: int = 0):
    """Tiny training loop; returns final loss (for smoke tests / demo pod)."""
    rng = jax.random.key(seed)
    params = init_params(rng)
    step_fn, opt = make_train_step(mesh)
    opt_state = opt.init(params)
    loss = None
    for i in range(steps):
        images, labels = synthetic_batch(jax.random.fold_in(jax.random.key(seed + 1), i), batch)
        params, opt_state, loss = step_fn(params, opt_state, images, labels)
    return float(loss)
