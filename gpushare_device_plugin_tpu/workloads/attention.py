"""Shared attention-implementation selection for the demo workloads.

One place for the trace-time gate that decides between the Pallas flash
kernel (``ops/flash_attention.py``) and plain softmax attention, and for
the shard_map wrapper that runs the kernel per-shard over the
(dp, fsdp, tp) mesh axes — used by both the decoder flagship
(``workloads/transformer.py``) and the BERT encoder (``workloads/bert.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import flash_attention
from ..ops.flash_attention import fits_kernel
from ..parallel.ring import grouped_attention


def grouped_full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool,
) -> jax.Array:
    """Plain attention with grouped KV heads (GQA) — no repeated KV.

    q: [B, S, H, Dh]; k, v: [B, S, Hkv, Dh] with H a multiple of Hkv. The
    group dim rides inside the einsums as a broadcast axis, so full-head
    K/V is never materialized in HBM. Delegates to the shared
    ``parallel.ring.grouped_attention`` math (f32 scores/softmax/
    accumulation — one implementation repo-wide).
    """
    return grouped_attention(q, k, v, causal=causal)


def chunk_prefill_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, n_real: jax.Array,
    attention: str = "auto",
) -> jax.Array:
    """Causal self-attention over a RIGHT-padded prompt chunk — the
    fresh-slot prefill of the continuous-batching engine
    (``workloads.generate.prefill_slot``).

    q: [B, C, H, Dh]; k, v: [B, C, Hkv, Dh]; ``n_real`` (traced scalar or
    [B]) counts each row's real tokens. Pads sit at the chunk's END, so
    causality already hides them from every real query — plain causal
    attention is exact as-is. The flash route forwards ``kv_len`` so the
    kernel skips pad KV blocks' MXU work and keeps fully-padded tail rows
    at exact zeros (the mirror image of the left-pad ``start`` input).
    """
    kv_len = jnp.broadcast_to(jnp.asarray(n_real, jnp.int32), (q.shape[0],))
    if use_flash(attention, q, None, kv_heads=k.shape[2]):
        return flash_attention(q, k, v, causal=True, kv_len=kv_len)
    return grouped_attention(q, k, v, causal=True)


def use_flash(
    attention: str,
    q: jax.Array,
    mesh: Mesh | None,
    kv_heads: int | None = None,
) -> bool:
    """Pick the attention implementation at trace time (shapes are static).

    "auto" engages the kernel only when every constraint of the shard_map
    route holds (batch divisible by dp*fsdp, both q and grouped-kv heads
    by tp, sequence by the kernel block) — otherwise it silently keeps the
    always-correct plain path. "flash" skips the checks so a misfit config
    fails loudly.
    """
    if attention == "flash":
        return True
    if attention == "plain":
        return False
    if attention != "auto":
        raise ValueError(f"unknown attention={attention!r}: expected auto|flash|plain")
    if jax.default_backend() != "tpu":
        return False
    B, S, H = q.shape[0], q.shape[1], q.shape[2]
    # The kernel module's own fit predicate (one copy repo-wide): a
    # multiple of 128 always lands on a legal block, and any 8-aligned S
    # up to 1024 runs as one whole-sequence block.
    if not fits_kernel(S, q.shape[-1]):
        return False
    if mesh is not None:
        data = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        tp = mesh.shape.get("tp", 1)
        if B % data or H % tp or (kv_heads or H) % tp:
            return False
    return True


def ulysses_inner_attn(attention: str):
    """Per-shard attention for the Ulysses a2a layout: full sequence,
    1/n of the (possibly grouped) heads — the flash kernel's home turf.
    Signature matches ``parallel.ulysses``'s ``attn_fn`` contract."""

    def fn(q, k, v, *, causal, scale):
        if scale is not None:
            raise ValueError(
                "ulysses_inner_attn uses the 1/sqrt(Dh) default scale"
            )
        if use_flash(attention, q, None, kv_heads=k.shape[2]):
            return flash_attention(q, k, v, causal=causal)
        return grouped_full_attention(q, k, v, causal=causal)

    return fn


def flash_or_plain(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    attention: str,
    causal: bool,
    mesh: Mesh | None,
) -> jax.Array:
    """Dispatch [B, S, H, Dh] attention to flash (per-shard) or plain.

    K/V may carry fewer (grouped/GQA) heads than Q; both paths consume
    them grouped end-to-end (the Pallas kernel is GQA-native — KV blocks
    stream at 1/g the bandwidth, never repeated in HBM).
    """
    if not use_flash(attention, q, mesh, kv_heads=k.shape[2]):
        return grouped_full_attention(q, k, v, causal=causal)
    if mesh is None:
        return flash_attention(q, k, v, causal=causal)
    # XLA cannot partition a custom call, so the kernel runs per-shard
    # under shard_map: batch over the data axes, heads over tp, sequence
    # replicated (sp-sharded sequences go through ring_attention instead).
    spec = P(("dp", "fsdp"), None, "tp", None)
    return jax.shard_map(
        functools.partial(flash_attention, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axes metadata;
        # the spec above is the full truth here (no collectives).
        check_vma=False,
    )(q, k, v)
