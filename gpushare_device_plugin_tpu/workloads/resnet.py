"""ResNet-50 image classifier, TPU-first (BASELINE.md config 3 workload).

Design notes (TPU/XLA):
- **NHWC + HWIO** layouts throughout — the native layouts for TPU convs;
  XLA lowers ``lax.conv_general_dilated`` straight onto the MXU without
  transposes.
- **bf16 compute, f32 params/stats** — kernels are cast to
  ``cfg.compute_dtype`` per-use; batch-norm statistics stay f32.
- **scan over the identical tail blocks of each stage** — the first block
  of a stage changes shape (stride/projection), the remaining ``n-1`` are
  shape-identical, so their params stack on a leading axis and run under
  one compiled `lax.scan` body: compile time stays flat as depth grows.
- **sharding** — batch shards over the data axes ``(dp, fsdp)``; the
  classifier head shards over ``tp``; conv kernels shard their output
  channel over ``fsdp`` (ZeRO-style, XLA all-gathers per block).

Functional batch-norm: ``forward`` takes and returns an explicit
``state`` pytree (running mean/var), train mode computes batch statistics
and folds them into the running averages — no mutation, jit-pure.

The reference has no model code (SURVEY.md section 2 — it schedules
containers); this is part of the workload half the TPU framework adds,
exercised by the two-pods-on-one-host demo (BASELINE.md config 3).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import commit_to_mesh, prune_unshardable

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    # (3, 4, 6, 3) is ResNet-50; tests use a tiny (1, 1, 1, 1) net.
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)
    width: int = 64
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @property
    def stage_features(self) -> tuple[int, ...]:
        return tuple(self.width * (2**i) for i in range(len(self.stage_sizes)))


# --- init -------------------------------------------------------------------


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout)) / jnp.sqrt(fan_in)).astype(
        jnp.float32
    )


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def _bn_state_init(c):
    return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}


def _bottleneck_init(key, cin, cmid, *, project):
    """Bottleneck: 1x1 reduce -> 3x3 -> 1x1 expand (x4), optional projection."""
    ks = jax.random.split(key, 4)
    cout = cmid * 4
    p = {
        "conv1": _conv_init(ks[0], 1, 1, cin, cmid),
        "bn1": _bn_init(cmid),
        "conv2": _conv_init(ks[1], 3, 3, cmid, cmid),
        "bn2": _bn_init(cmid),
        "conv3": _conv_init(ks[2], 1, 1, cmid, cout),
        "bn3": _bn_init(cout),
    }
    if project:
        p["proj"] = _conv_init(ks[3], 1, 1, cin, cout)
        p["bn_proj"] = _bn_init(cout)
    return p


def _bottleneck_state(cmid, *, project):
    s = {
        "bn1": _bn_state_init(cmid),
        "bn2": _bn_state_init(cmid),
        "bn3": _bn_state_init(cmid * 4),
    }
    if project:
        s["bn_proj"] = _bn_state_init(cmid * 4)
    return s


def init_params(rng: jax.Array, cfg: ResNetConfig) -> tuple[Params, Params]:
    """Returns (params, state) — state is the running batch-norm statistics."""
    n_stages = len(cfg.stage_sizes)
    keys = jax.random.split(rng, n_stages + 2)
    params: Params = {
        "stem": {"conv": _conv_init(keys[0], 7, 7, 3, cfg.width), "bn": _bn_init(cfg.width)},
    }
    state: Params = {"stem": {"bn": _bn_state_init(cfg.width)}}
    cin = cfg.width
    for i, (n_blocks, cmid) in enumerate(zip(cfg.stage_sizes, cfg.stage_features)):
        bks = jax.random.split(keys[i + 1], n_blocks)
        head = _bottleneck_init(bks[0], cin, cmid, project=True)
        stage = {"head": head}
        sstate = {"head": _bottleneck_state(cmid, project=True)}
        if n_blocks > 1:
            # Tail blocks are shape-identical: stack on a leading axis for scan.
            tails = [
                _bottleneck_init(bk, cmid * 4, cmid, project=False)
                for bk in bks[1:]
            ]
            stage["tail"] = jax.tree.map(lambda *xs: jnp.stack(xs), *tails)
            tstates = [_bottleneck_state(cmid, project=False) for _ in bks[1:]]
            sstate["tail"] = jax.tree.map(lambda *xs: jnp.stack(xs), *tstates)
        params[f"stage{i}"] = stage
        state[f"stage{i}"] = sstate
        cin = cmid * 4
    params["head"] = {
        "kernel": (
            jax.random.normal(keys[-1], (cin, cfg.num_classes)) / jnp.sqrt(cin)
        ).astype(jnp.float32),
        "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, state


def param_specs(cfg: ResNetConfig) -> Params:
    """PartitionSpec pytree matching :func:`init_params`'s params.

    Conv kernels ZeRO-shard their output channel over ``fsdp``; the dense
    classifier shards classes over ``tp``. BN vectors stay replicated.
    """

    def spec_for(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        parent = path[-2].key if len(path) > 1 and hasattr(path[-2], "key") else ""
        rank = leaf.ndim
        if parent in ("bn1", "bn2", "bn3", "bn_proj", "bn"):
            return P(*([None] * rank))
        if name == "kernel":
            return P("fsdp", "tp")
        if name == "bias":
            return P("tp")
        # conv kernels: [(L,)? kh, kw, cin, cout] -> shard cout over fsdp
        return P(*([None] * (rank - 1)), "fsdp")

    params, _ = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    return jax.tree_util.tree_map_with_path(spec_for, params)


def param_shardings(mesh: Mesh, cfg: ResNetConfig) -> Params:
    abstract, _ = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    specs = prune_unshardable(param_specs(cfg), abstract, mesh)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, state: Params, mesh: Mesh, cfg: ResNetConfig):
    replicated = NamedSharding(mesh, P())
    return (
        jax.device_put(params, param_shardings(mesh, cfg)),
        jax.device_put(state, jax.tree.map(lambda _: replicated, state)),
    )


# --- model ------------------------------------------------------------------


def _conv(x, kernel, *, stride=1, dtype=None):
    return jax.lax.conv_general_dilated(
        x,
        kernel.astype(dtype or x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, p, s, *, train, momentum, eps):
    """Returns (y, new_state). Statistics in f32 regardless of compute dtype."""
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps) * p["scale"]
    y = (x.astype(jnp.float32) - mean) * inv + p["bias"]
    return y.astype(x.dtype), new_s


def _bottleneck(x, p, s, cfg: ResNetConfig, *, stride=1, train):
    bn = functools.partial(
        _batch_norm, train=train, momentum=cfg.bn_momentum, eps=cfg.bn_eps
    )
    dt = cfg.compute_dtype
    ns = {}
    h = _conv(x, p["conv1"], dtype=dt)
    h, ns["bn1"] = bn(h, p["bn1"], s["bn1"])
    h = jax.nn.relu(h)
    h = _conv(h, p["conv2"], stride=stride, dtype=dt)
    h, ns["bn2"] = bn(h, p["bn2"], s["bn2"])
    h = jax.nn.relu(h)
    h = _conv(h, p["conv3"], dtype=dt)
    h, ns["bn3"] = bn(h, p["bn3"], s["bn3"])
    if "proj" in p:
        x = _conv(x, p["proj"], stride=stride, dtype=dt)
        x, ns["bn_proj"] = bn(x, p["bn_proj"], s["bn_proj"])
    return jax.nn.relu(x + h), ns


def forward(
    params: Params,
    state: Params,
    images: jax.Array,
    cfg: ResNetConfig,
    *,
    train: bool = True,
) -> tuple[jax.Array, Params]:
    """images: [B, H, W, 3] -> (logits [B, classes] f32, new_state)."""
    dt = cfg.compute_dtype
    x = images.astype(dt)
    new_state: Params = {}
    x = _conv(x, params["stem"]["conv"], stride=2, dtype=dt)
    x, stem_bn = _batch_norm(
        x, params["stem"]["bn"], state["stem"]["bn"],
        train=train, momentum=cfg.bn_momentum, eps=cfg.bn_eps,
    )
    new_state["stem"] = {"bn": stem_bn}
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for i, n_blocks in enumerate(cfg.stage_sizes):
        sp, ss = params[f"stage{i}"], state[f"stage{i}"]
        stride = 1 if i == 0 else 2
        x, head_ns = _bottleneck(x, sp["head"], ss["head"], cfg, stride=stride, train=train)
        stage_ns = {"head": head_ns}
        if n_blocks > 1:

            def body(carry, block):
                bp, bs = block
                y, ns = _bottleneck(carry, bp, bs, cfg, train=train)
                return y, ns

            x, tail_ns = jax.lax.scan(body, x, (sp["tail"], ss["tail"]))
            stage_ns["tail"] = tail_ns
        new_state[f"stage{i}"] = stage_ns
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    logits = x @ params["head"]["kernel"] + params["head"]["bias"]
    return logits, new_state


def loss_fn(params, state, images, labels, cfg: ResNetConfig):
    logits, new_state = forward(params, state, images, cfg, train=True)
    nll = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(nll), new_state


# --- training ---------------------------------------------------------------


def make_optimizer(lr: float = 0.1) -> optax.GradientTransformation:
    return optax.sgd(lr, momentum=0.9, nesterov=True)


def make_train_step(mesh: Mesh, cfg: ResNetConfig, optimizer=None):
    """(params, state, opt_state, images, labels) -> (params, state, opt_state, loss)."""
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    lbl_sh = NamedSharding(mesh, P(("dp", "fsdp")))
    img_sh = NamedSharding(mesh, P(("dp", "fsdp"), None, None, None))

    def step(params, state, opt_state, images, labels):
        (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, images, labels, cfg
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_state, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(psh, None, None, img_sh, lbl_sh),
        out_shardings=(psh, None, None, None),
        donate_argnums=(0, 1, 2),
    )


def init_train_state(rng: jax.Array, mesh: Mesh, cfg: ResNetConfig, optimizer=None):
    """Init under jit with ``out_shardings``: weights are created in-shard
    (see transformer.init_train_state for why)."""
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    ssh = jax.tree.map(
        lambda _: NamedSharding(mesh, P()),
        jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))[1],
    )
    params, state = jax.jit(
        lambda k: init_params(k, cfg), out_shardings=(psh, ssh)
    )(rng)
    opt_state = commit_to_mesh(opt.init(params), mesh)  # see transformer
    return params, state, opt_state


def demo_batch(rng: jax.Array, batch: int, size: int = 32):
    """Synthetic images+labels (zero-egress image: no dataset downloads)."""
    k_img, k_lbl = jax.random.split(rng)
    images = jax.random.uniform(k_img, (batch, size, size, 3), jnp.float32)
    labels = jax.random.randint(k_lbl, (batch,), 0, 10)
    return images, labels


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), width=64, num_classes=num_classes)
