"""Autoregressive generation with a KV cache for the decoder flagship.

The training half (``transformer.py``) covers the reference-parity story;
this is the inference half a framework user expects: prefill + cached
decode, compiled end to end.

TPU-first design:

- **Static shapes everywhere.** The cache is a fixed ``[L, B, Smax, Hkv,
  Dh]`` buffer; the decode loop is a ``lax.scan`` of static trip count
  (``max_new``), so XLA compiles ONE program — no per-token retracing, no
  dynamic shapes blocking MXU tiling. Early stop on EOS is a post-hoc mask
  (XLA-friendly), not a data-dependent loop break.
- **Prefill is the training forward** (flash attention when on TPU) plus
  cache writes; decode attention is a single-query masked attention over
  the cache — a [B,H,1,S] einsum the MXU handles without a custom kernel.
- **GQA-native end to end**: the cache stores ``Hkv`` heads (1/g the HBM
  of full-head caching, the whole point of GQA at serving time); the query
  group dimension rides inside the einsums.

Single-host scope: generation targets one chip (or auto-SPMD under jit on
a mesh via sharded params); the sp-ring path is a training concern.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from .attention import flash_or_plain
from .transformer import TransformerConfig, _mlp_block, _project_qkv, _rms_norm

KVCache = dict[str, jax.Array]  # {"k","v"}: [L, B, Smax, Hkv, Dh]; "len": []


def init_cache(cfg: TransformerConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def _decode_attention(q, k_cache, v_cache, cur_len):
    """Single-position attention over the cache.

    q: [B, 1, H, Dh]; k_cache/v_cache: [B, Smax, Hkv, Dh]; positions
    ``>= cur_len`` (the unwritten tail) are masked out. f32 softmax like
    every other attention path in the repo.
    """
    B, _, H, Dh = q.shape
    Smax = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    g = H // Hkv
    qg = q[:, 0].reshape(B, Hkv, g, Dh)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k_cache).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(Dh))
    mask = jnp.arange(Smax) < cur_len  # [Smax]
    s = jnp.where(mask[None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p.astype(v_cache.dtype), v_cache)
    return out.reshape(B, 1, H, Dh)


def prefill(
    params: Any, tokens: jax.Array, cache: KVCache, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling the cache.

    tokens: [B, Tp] -> (last-position logits [B, vocab], cache with
    ``len=Tp``). Prompt self-attention is the training attention path
    (flash on TPU); the cache is written, not read — prefill always starts
    a fresh sequence.
    """
    dt = cfg.compute_dtype
    B, Tp = tokens.shape
    positions = jnp.arange(Tp)
    x = params["embed"].astype(dt)[tokens]

    def layer(x, xs):
        lp, _ = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        attn = flash_or_plain(
            q, k, v, attention=cfg.attention, causal=True, mesh=None
        )
        x = x + jnp.einsum("bthn,hnd->btd", attn, lp["wo"].astype(dt))
        return _mlp_block(x, lp, cfg), (k, v)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(cfg.n_layers))
    )
    # ks/vs: [L, B, Tp, Hkv, Dh] -> cache[:, :, :Tp]
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
        ),
        "len": jnp.int32(Tp),
    }
    x = _rms_norm(x[:, -1:], params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, params["out"].astype(dt))
    return logits[:, 0].astype(jnp.float32), cache


def decode_step(
    params: Any, token: jax.Array, cache: KVCache, cfg: TransformerConfig
) -> tuple[jax.Array, KVCache]:
    """One cached decode step. token: [B] -> (logits [B, vocab], cache+1)."""
    dt = cfg.compute_dtype
    pos = cache["len"]
    positions = pos[None]  # [1]
    x = params["embed"].astype(dt)[token][:, None]  # [B, 1, d]

    def layer(x, xs):
        lp, k_cache, v_cache = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0)
        )
        attn = _decode_attention(q, k_cache, v_cache, pos + 1)
        x = x + jnp.einsum("bthn,hnd->btd", attn, lp["wo"].astype(dt))
        return _mlp_block(x, lp, cfg), (k_cache, v_cache)

    x, (ks, vs) = jax.lax.scan(layer, x, (params["layers"], cache["k"], cache["v"]))
    cache = {"k": ks, "v": vs, "len": pos + 1}
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, params["out"].astype(dt))
    return logits[:, 0].astype(jnp.float32), cache


def generate(
    params: Any,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    eos_id: int | None = None,
) -> jax.Array:
    """Generate ``max_new`` tokens after ``prompt`` ([B, Tp] int32).

    Returns [B, Tp + max_new]. ``temperature=0`` is greedy argmax;
    otherwise softmax sampling at the given temperature (``rng``
    required). With ``eos_id``, positions after the first EOS are
    overwritten with EOS (post-hoc mask — the compiled loop always runs
    ``max_new`` steps; see module docstring).

    Wrap in ``jax.jit`` with ``static_argnames=()`` via
    :func:`make_generate` for repeated use.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs rng")
    B, Tp = prompt.shape
    cache = init_cache(cfg, B, Tp + max_new)
    logits, cache = prefill(params, prompt, cache, cfg)
    rng = rng if rng is not None else jax.random.key(0)

    def pick(logits, key):
        if temperature > 0.0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    rng, k0 = jax.random.split(rng)
    first = pick(logits, k0).astype(jnp.int32)  # [B]

    def step(carry, _):
        token, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = decode_step(params, token, cache, cfg)
        nxt = pick(logits, sub).astype(jnp.int32)
        return (nxt, cache, key), token

    (_last, cache, _), toks = jax.lax.scan(
        step, (first, cache, rng), None, length=max_new
    )
    out = jnp.concatenate([prompt, toks.T], axis=1)  # [B, Tp + max_new]
    if eos_id is not None:
        gen = out[:, Tp:]
        seen = jnp.cumsum((gen == eos_id).astype(jnp.int32), axis=1)
        # positions strictly after the first EOS become EOS
        gen = jnp.where(seen - (gen == eos_id) > 0, eos_id, gen)
        out = jnp.concatenate([out[:, :Tp], gen], axis=1)
    return out


def make_generate(cfg: TransformerConfig, *, max_new: int, temperature: float = 0.0):
    """Jitted (params, prompt, rng) -> tokens closure (one compile per
    prompt shape)."""
    fn = functools.partial(
        generate, cfg=cfg, max_new=max_new, temperature=temperature
    )
    return jax.jit(lambda params, prompt, rng: fn(params, prompt, rng=rng))
