"""Autoregressive generation with a KV cache for the decoder flagship.

The training half (``transformer.py``) covers the reference-parity story;
this is the inference half a framework user expects: prefill + cached
decode, compiled end to end.

TPU-first design:

- **Static shapes everywhere.** The cache is a fixed ``[L, B, Smax, Hkv,
  Dh]`` buffer; the decode loop is a ``lax.scan`` of static trip count
  (``max_new``), so XLA compiles ONE program — no per-token retracing, no
  dynamic shapes blocking MXU tiling. Early stop on EOS is a post-hoc mask
  (XLA-friendly), not a data-dependent loop break.
- **Prefill is the training forward** (flash attention when on TPU) plus
  cache writes; decode attention is a single-query masked attention over
  the cache — a [B,H,1,S] einsum the MXU handles without a custom kernel.
- **GQA-native end to end**: the cache stores ``Hkv`` heads (1/g the HBM
  of full-head caching, the whole point of GQA at serving time); the query
  group dimension rides inside the einsums.

Single-host scope: generation targets one chip (or auto-SPMD under jit on
a mesh via sharded params); the sp-ring path is a training concern.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..ops import flash_attention
from ..parallel.ring import grouped_attention
from .attention import flash_or_plain, use_flash
from .quant import (
    dequantize_kv,
    embed_lookup,
    matmul_weight,
    quantize_kv,
)
from .transformer import TransformerConfig, _mlp_block, _project_qkv, _rms_norm

# {"k","v"}: [L, B, Smax, Hkv, Dh]; "len": []. int8 caches additionally
# carry {"k_scale","v_scale"}: [L, B, Smax, Hkv] f32 (see init_cache).
KVCache = dict[str, jax.Array]


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int,
    kv_dtype: str | None = None,
) -> KVCache:
    """Fresh cache. ``kv_dtype="int8"`` stores K/V as symmetric int8 with
    per-(token, head) scales (``quant.quantize_kv``) — half the cache HBM
    of bf16, which is both the decode bandwidth floor at long context and
    the slice a fractional-HBM pod must reserve for it. Dequantization
    fuses into the attention einsums; entries are quantized once, at
    write time."""
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"unknown kv_dtype={kv_dtype!r}: expected None|'int8'")
    if kv_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(shape[:-1], jnp.float32),
            "v_scale": jnp.ones(shape[:-1], jnp.float32),
            "len": jnp.zeros((), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def _cache_is_q8(cache: KVCache) -> bool:
    return "k_scale" in cache


def _decode_attention(q, k_cache, v_cache, cur_len, start=None):
    """Single-position attention over the cache.

    q: [B, 1, H, Dh]; k_cache/v_cache: [B, Smax, Hkv, Dh]; positions
    ``>= cur_len`` (the unwritten tail) are masked out, as are positions
    ``< start[b]`` (per-row left padding). f32 softmax like every other
    attention path in the repo.
    """
    B, _, H, Dh = q.shape
    Smax = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    g = H // Hkv
    qg = q[:, 0].reshape(B, Hkv, g, Dh)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k_cache).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(Dh))
    idx = jnp.arange(Smax)
    mask = jnp.broadcast_to(idx < cur_len, (B, Smax))
    if start is not None:
        mask = mask & (idx[None, :] >= start[:, None])
    s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    # f32 accumulation over the key axis; cast once at the end.
    out = jnp.einsum("bkgs,bskd->bkgd", p, v_cache).astype(q.dtype)
    return out.reshape(B, 1, H, Dh)


def _padded_prefill_attention(q, k, v, pad, attention: str = "auto"):
    """Prompt self-attention with per-row left padding.

    q: [B, T, H, Dh]; k, v: [B, T, Hkv, Dh]; pad: [B] leading pad counts.
    On TPU this stays on the flash kernel via its ``start`` input (pad
    keys masked in-kernel, O(T·Dh) HBM) — a serving-realistic 4-8k prompt
    through materialized-score attention would be exactly the quadratic
    HBM traffic the kernel exists to avoid. Off-TPU (or misfit shapes)
    it delegates to the shared grouped-attention math with an explicit
    key mask.
    """
    if use_flash(attention, q, None, kv_heads=k.shape[2]):
        return flash_attention(q, k, v, causal=True, start=pad)
    T = q.shape[1]
    live = jnp.arange(T)[None, :] >= pad[:, None]  # [B, Tk]
    return grouped_attention(
        q, k, v, causal=True, mask=jnp.broadcast_to(live[:, None, :], (q.shape[0], T, T))
    )


def prefill(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    pad: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling the cache.

    tokens: [B, Tp] -> (last-position logits [B, vocab], cache with
    ``len=Tp``). Prompt self-attention is the training attention path
    (flash on TPU); the cache is written, not read — prefill always starts
    a fresh sequence.

    ``pad`` ([B] leading pad counts) switches to LEFT-padded variable-
    length mode: RoPE positions are offset per row, pad keys are masked,
    and the last position holds every row's final real token.
    """
    dt = cfg.compute_dtype
    B, Tp = tokens.shape
    if pad is None:
        positions = jnp.arange(Tp)
    else:
        positions = jnp.clip(jnp.arange(Tp)[None, :] - pad[:, None], 0)
    x = embed_lookup(params["embed"], tokens, dt)

    def layer(x, xs):
        lp, _ = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        if pad is None:
            attn = flash_or_plain(
                q, k, v, attention=cfg.attention, causal=True, mesh=None
            )
        else:
            attn = _padded_prefill_attention(q, k, v, pad, cfg.attention)
        x = x + jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        return _mlp_block(x, lp, cfg), (k, v)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(cfg.n_layers))
    )
    # ks/vs: [L, B, Tp, Hkv, Dh] -> cache[:, :, :Tp]
    if _cache_is_q8(cache):
        kq8, kscale = quantize_kv(ks)
        vq8, vscale = quantize_kv(vs)
        cache = {
            "k": jax.lax.dynamic_update_slice(cache["k"], kq8, (0, 0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(cache["v"], vq8, (0, 0, 0, 0, 0)),
            "k_scale": jax.lax.dynamic_update_slice(
                cache["k_scale"], kscale, (0, 0, 0, 0)
            ),
            "v_scale": jax.lax.dynamic_update_slice(
                cache["v_scale"], vscale, (0, 0, 0, 0)
            ),
            "len": jnp.int32(Tp),
        }
    else:
        cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
            ),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
            ),
            "len": jnp.int32(Tp),
        }
    x = _rms_norm(x[:, -1:], params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, matmul_weight(params["out"], dt))
    return logits[:, 0].astype(jnp.float32), cache


def decode_step(
    params: Any,
    token: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    start: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """One cached decode step. token: [B] -> (logits [B, vocab], cache+1).

    ``start`` ([B] leading pad counts from a left-padded prefill) offsets
    each row's RoPE position and masks its pad slots out of attention.
    """
    dt = cfg.compute_dtype
    pos = cache["len"]
    if start is None:
        positions = pos[None]  # [1]
    else:
        positions = (pos - start)[:, None]  # [B, 1]
    x = embed_lookup(params["embed"], token, dt)[:, None]  # [B, 1, d]

    q8 = _cache_is_q8(cache)

    def layer(x, xs):
        if q8:
            lp, k_cache, v_cache, k_scale, v_scale = xs
        else:
            lp, k_cache, v_cache = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        if q8:
            kq8, ks_new = quantize_kv(k)
            vq8, vs_new = quantize_kv(v)
            k_cache = jax.lax.dynamic_update_slice(k_cache, kq8, (0, pos, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(v_cache, vq8, (0, pos, 0, 0))
            k_scale = jax.lax.dynamic_update_slice(k_scale, ks_new, (0, pos, 0))
            v_scale = jax.lax.dynamic_update_slice(v_scale, vs_new, (0, pos, 0))
            # Dequant fuses into the attention einsums; HBM holds int8.
            k_mat = dequantize_kv(k_cache, k_scale, q.dtype)
            v_mat = dequantize_kv(v_cache, v_scale, q.dtype)
            carry = (k_cache, v_cache, k_scale, v_scale)
        else:
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0)
            )
            k_mat, v_mat = k_cache, v_cache
            carry = (k_cache, v_cache)
        attn = _decode_attention(q, k_mat, v_mat, pos + 1, start=start)
        x = x + jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        return _mlp_block(x, lp, cfg), carry

    if q8:
        xs = (
            params["layers"], cache["k"], cache["v"],
            cache["k_scale"], cache["v_scale"],
        )
        x, (ks, vs, kss, vss) = jax.lax.scan(layer, x, xs)
        cache = {
            "k": ks, "v": vs, "k_scale": kss, "v_scale": vss, "len": pos + 1,
        }
    else:
        x, (ks, vs) = jax.lax.scan(
            layer, x, (params["layers"], cache["k"], cache["v"])
        )
        cache = {"k": ks, "v": vs, "len": pos + 1}
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, matmul_weight(params["out"], dt))
    return logits[:, 0].astype(jnp.float32), cache


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Sample next tokens from ``[B, vocab]`` logits (compiled-friendly).

    ``temperature=0`` is greedy argmax (top_k/top_p ignored). Otherwise
    softmax sampling at the given temperature, optionally restricted to
    the ``top_k`` highest logits and/or the smallest set of tokens whose
    probability mass reaches ``top_p`` (nucleus). Both filters are static
    masks over sorted logits — no dynamic shapes, one compiled program.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # Clamp to the vocab (sampler-config portability: top_k=50 on a
        # small-vocab model means "no truncation", not a trace error).
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens while the mass BEFORE them is < top_p (the first
        # token is always kept); find the smallest kept logit.
        keep = (cum - probs) < top_p  # [B, vocab] over sorted order
        # smallest kept logit per row = min over kept sorted logits
        floor = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < floor, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(
    params: Any,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    eos_id: int | None = None,
    prompt_lens: jax.Array | None = None,
    kv_dtype: str | None = None,
) -> jax.Array:
    """Generate ``max_new`` tokens after ``prompt`` ([B, Tp] int32).

    Returns [B, Tp + max_new]; with ``prompt_lens`` (variable-length
    batch), returns ONLY the generated block [B, max_new] — row i's
    tokens logically continue from position ``prompt_lens[i]``, so a
    concatenated layout would be ragged. ``prompt`` is right-padded as
    given; it is re-packed LEFT-padded internally so every row's decode
    writes the same cache slot (static shapes, no per-row scatter).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (``rng`` required), optionally truncated by
    ``top_k`` and/or nucleus ``top_p`` (:func:`sample_logits`). With
    ``eos_id``, positions after the first EOS are overwritten with EOS
    (post-hoc mask — the compiled loop always runs ``max_new`` steps;
    see module docstring).

    Wrap in ``jax.jit`` via :func:`make_generate` for repeated use.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs rng")
    B, Tp = prompt.shape
    cache = init_cache(cfg, B, Tp + max_new, kv_dtype=kv_dtype)
    pad = None
    if prompt_lens is not None:
        pad = (Tp - prompt_lens).astype(jnp.int32)
        # right-padded -> left-padded: roll each row by its pad count
        prompt_packed = jax.vmap(jnp.roll)(prompt, pad)
        logits, cache = prefill(params, prompt_packed, cache, cfg, pad=pad)
    else:
        logits, cache = prefill(params, prompt, cache, cfg)
    rng = rng if rng is not None else jax.random.key(0)

    def pick(logits, key):
        return sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )

    rng, k0 = jax.random.split(rng)
    first = pick(logits, k0).astype(jnp.int32)  # [B]

    def step(carry, _):
        token, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = decode_step(params, token, cache, cfg, start=pad)
        nxt = pick(logits, sub).astype(jnp.int32)
        return (nxt, cache, key), token

    (_last, cache, _), toks = jax.lax.scan(
        step, (first, cache, rng), None, length=max_new
    )
    gen = toks.T  # [B, max_new]
    if eos_id is not None:
        seen = jnp.cumsum((gen == eos_id).astype(jnp.int32), axis=1)
        # positions strictly after the first EOS become EOS
        gen = jnp.where(seen - (gen == eos_id) > 0, eos_id, gen)
    if prompt_lens is not None:
        return gen
    return jnp.concatenate([prompt, gen], axis=1)  # [B, Tp + max_new]


def make_generate(
    cfg: TransformerConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    padded: bool = False,
    kv_dtype: str | None = None,
):
    """Jitted generate closure (one compile per prompt shape).

    ``padded=False``: (params, prompt, rng) -> [B, Tp+max_new].
    ``padded=True``: (params, prompt, prompt_lens, rng) -> [B, max_new]
    (the variable-length serving path). ``kv_dtype="int8"`` serves from a
    half-size quantized KV cache (see :func:`init_cache`); sampling
    controls per :func:`sample_logits`.
    """
    fn = functools.partial(
        generate, cfg=cfg, max_new=max_new, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_id=eos_id, kv_dtype=kv_dtype,
    )
    if padded:
        return jax.jit(
            lambda params, prompt, prompt_lens, rng: fn(
                params, prompt, rng=rng, prompt_lens=prompt_lens
            )
        )
    return jax.jit(lambda params, prompt, rng: fn(params, prompt, rng=rng))
