"""Autoregressive generation with a KV cache for the decoder flagship.

The training half (``transformer.py``) covers the reference-parity story;
this is the inference half a framework user expects: prefill + cached
decode, compiled end to end.

TPU-first design:

- **Static shapes everywhere.** The cache is a fixed ``[L, B, Smax, Hkv,
  Dh]`` buffer; the decode loop is a ``lax.scan`` of static trip count
  (``max_new``), so XLA compiles ONE program — no per-token retracing, no
  dynamic shapes blocking MXU tiling. Early stop on EOS is a post-hoc mask
  (XLA-friendly), not a data-dependent loop break.
- **Prefill is the training forward** (flash attention when on TPU) plus
  cache writes; decode attention is a single-query masked attention over
  the cache — a [B,H,1,S] einsum the MXU handles without a custom kernel.
- **GQA-native end to end**: the cache stores ``Hkv`` heads (1/g the HBM
  of full-head caching, the whole point of GQA at serving time); the query
  group dimension rides inside the einsums.
- **Speculative decoding**: :func:`decode_block` verifies a k-token draft
  in one cached forward; :func:`speculative_generate` wraps the
  draft/verify/accept loop in a ``lax.while_loop`` with static shapes
  (cache ``len`` rewinds past rejected entries; stale positions stay
  masked), emitting exactly the target model's greedy tokens.

Single-host scope: generation targets one chip (or auto-SPMD under jit on
a mesh via sharded params); the sp-ring path is a training concern.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..ops import flash_attention
from ..parallel.ring import grouped_attention
from .attention import chunk_prefill_attention, flash_or_plain, use_flash
from .lora import LoraConfig, lora_flat_len, unflatten_lora
from .quant import (
    dequantize_kv,
    embed_lookup,
    matmul_weight,
    quantize_kv,
)
from .transformer import (
    TransformerConfig,
    _bgmv_delta,
    _mlp_block,
    _project_qkv,
    _rms_norm,
)

# {"k","v"}: [L, B, Smax, Hkv, Dh]; "len": [] (batch caches) or [B]
# (slot-pool caches, one independent sequence length per row — the
# continuous-batching layout, see init_slot_cache). int8 caches
# additionally carry {"k_scale","v_scale"}: [L, B, Smax, Hkv] f32 (see
# init_cache).
KVCache = dict[str, jax.Array]


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int,
    kv_dtype: str | None = None,
) -> KVCache:
    """Fresh cache. ``kv_dtype="int8"`` stores K/V as symmetric int8 with
    per-(token, head) scales (``quant.quantize_kv``) — half the cache HBM
    of bf16, which is both the decode bandwidth floor at long context and
    the slice a fractional-HBM pod must reserve for it. Dequantization
    fuses into the attention einsums; entries are quantized once, at
    write time."""
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"unknown kv_dtype={kv_dtype!r}: expected None|'int8'")
    if kv_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(shape[:-1], jnp.float32),
            "v_scale": jnp.ones(shape[:-1], jnp.float32),
            "len": jnp.zeros((), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def init_slot_cache(
    cfg: TransformerConfig, slots: int, max_len: int,
    kv_dtype: str | None = None,
) -> KVCache:
    """Slot-pool cache for the continuous-batching engine
    (``serving.engine``): same buffers as :func:`init_cache`, but ``len``
    is a ``[slots]`` vector — every row is an independent sequence that
    starts at its own position 0 and advances at its own pace, so a
    retired row can be re-packed with a new request while its neighbors
    keep decoding. All slot rows share one set of static-shaped buffers:
    admission and retirement never change a traced shape."""
    cache = init_cache(cfg, slots, max_len, kv_dtype=kv_dtype)
    return {**cache, "len": jnp.zeros((slots,), jnp.int32)}


def init_paged_cache(
    cfg: TransformerConfig, slots: int, pages: int, page_size: int,
    kv_dtype: str | None = None,
) -> KVCache:
    """Paged slot-pool cache for the paged serving engine
    (``serving/engine.py`` + ``serving/pages.py``): K/V live in ``pages``
    fixed-size pages — physical ``[L, pages, page_size, Hkv, Dh]`` (int8
    scales ``[L, pages, page_size, Hkv]``) — and every request reads and
    writes through a per-row **page table** instead of owning a
    contiguous ``max_len`` row. ``pages`` counts PHYSICAL pages including
    the scratch page (``serving.pages.SCRATCH``, id 0) that idle rows'
    tables point at. ``len`` stays the per-row ``[slots]`` vector of the
    slot pool; the batch axis of the K/V buffers is now pages, not slots.
    """
    cache = init_cache(cfg, pages, page_size, kv_dtype=kv_dtype)
    return {**cache, "len": jnp.zeros((slots,), jnp.int32)}


def _gather_paged(cache: KVCache, page_tables: jax.Array) -> KVCache:
    """Materialize logical rows from a paged cache: ``page_tables``
    ``[B, MP]`` physical page ids -> a view ``{k, v, (scales)}`` of shape
    ``[L, B, MP*page_size, ...]`` — exactly the contiguous slot-pool
    layout, so :func:`decode_block` runs on it unchanged and its logits
    are bitwise what the contiguous engine computes (the gather copies
    values; positions beyond each row's ``len`` stay invisible by the
    same mask that hides a retired occupant's stale KV)."""
    out: KVCache = {}
    for key, val in cache.items():
        if key == "len":
            continue
        ps = val.shape[2]
        g = jnp.take(val, page_tables, axis=1)  # [L, B, MP, ps, ...]
        out[key] = g.reshape(
            (g.shape[0], g.shape[1], g.shape[2] * ps) + g.shape[4:]
        )
    return out


def _paged_write(
    cache: KVCache,
    new: dict[str, jax.Array],
    page_table: jax.Array,
    logical: jax.Array,
) -> KVCache:
    """Scatter per-position K/V (``new[key]``: ``[L, N, ...]`` for
    logical positions ``logical`` ``[N]``) into the physical pages named
    by ``page_table`` ``[MP]``: position ``p`` lands at
    ``(page_table[p // ps], p % ps)``. Duplicate targets (idle rows
    parked on the scratch page) resolve arbitrarily — by construction
    nothing ever reads them."""
    ps = cache["k"].shape[2]
    pids = jnp.take(page_table, logical // ps)
    offs = logical % ps
    out = dict(cache)
    for key, val in new.items():
        out[key] = cache[key].at[:, pids, offs].set(val)
    return out


def lora_bgmv_views(
    slab: jax.Array,
    tables: jax.Array,
    cfg: TransformerConfig,
    lcfg: LoraConfig,
) -> dict[str, tuple[jax.Array, jax.Array]]:
    """Gather per-slot adapters from the paged slab into BGMV scan views.

    ``slab``: ``[pages, page_floats]`` f32 — every adapter's canonical
    flat vector (``lora.flatten_lora``) striped across pages of the SAME
    id space as the KV pool; row 0 is the scratch page and stays
    permanently zero. ``tables``: ``[B, AP]`` int32 per-slot adapter page
    ids — a base-model slot's all-scratch table gathers an all-zero
    vector, whose low-rank delta is exactly zero (the null adapter).

    Returns ``{target: (a [L, B, fi, r], b [L, B, r, fo])}``, layer-major
    so the views ride :func:`decode_block`'s ``lax.scan`` as xs. Adapter
    identity lives entirely in the gathered VALUES: swapping which
    adapter a slot runs changes ``tables`` (data), never a shape, so a
    batch mixing arbitrary adapters — or none — is one compiled dispatch.
    """
    B = tables.shape[0]
    F = lora_flat_len(cfg, lcfg)
    flat = jnp.take(slab, tables, axis=0)  # [B, AP, page_floats]
    flat = flat.reshape(B, -1)[:, :F]  # [B, F] (tail page slack dropped)
    views = unflatten_lora(flat, cfg, lcfg)  # {t: ([B,L,fi,r], [B,L,r,fo])}
    return {
        name: (jnp.moveaxis(a, 1, 0), jnp.moveaxis(b, 1, 0))
        for name, (a, b) in views.items()
    }


def _lora_wo_delta(attn, lora_l, lora_scale: float, dt):
    """The wo-projection BGMV hook at decode/prefill wo einsum sites:
    attn [B, T, H, Dh] -> [B, T, d] delta (or None without a wo target)."""
    if lora_l is None or "wo" not in lora_l:
        return None
    a, b = lora_l["wo"]
    flat = attn.reshape(attn.shape[0], attn.shape[1], -1)  # [B, T, H*Dh]
    return _bgmv_delta(flat, a, b, lora_scale, dt)


def paged_prefill_slot(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    slot: jax.Array,
    page_table: jax.Array,
    n_real: jax.Array,
    lora: dict[str, tuple[jax.Array, jax.Array]] | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, KVCache]:
    """:func:`prefill_slot` through a page table: pack one request's
    OPENING prompt chunk (``tokens`` [C] right-padded, ``n_real`` real)
    into the pages of row ``slot``, restarting the row at logical
    position 0. The chunk's self-attention is identical to
    :func:`prefill_slot` (causal over the chunk; pads at the end are
    invisible); only the cache write changes — positions ``0..C-1``
    scatter through ``page_table`` ([MP] physical ids) instead of a
    contiguous row, so the row only pins the pages its tokens occupy.
    Returns the last real position's logits ``[1, vocab]`` f32 and the
    updated cache, bitwise :func:`prefill_slot`'s for the same tokens.
    ``lora``: the slot's B=1 adapter views (:func:`lora_bgmv_views` on a
    ``[1, AP]`` table), applied at every projection site as in
    :func:`decode_block`.
    """
    dt = cfg.compute_dtype
    C = tokens.shape[0]
    positions = jnp.arange(C)[None, :]
    x = embed_lookup(params["embed"], tokens[None, :], dt)  # [1, C, d]

    def layer(x, xs):
        if lora is not None:
            lp, _, lora_l = xs
        else:
            lp, _ = xs
            lora_l = None
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(
            h, lp, cfg, positions, lora=lora_l, lora_scale=lora_scale
        )
        attn = chunk_prefill_attention(q, k, v, n_real=n_real, attention=cfg.attention)
        wo = jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        wo_delta = _lora_wo_delta(attn, lora_l, lora_scale, dt)
        if wo_delta is not None:
            wo = wo + wo_delta
        x = x + wo
        return _mlp_block(x, lp, cfg, lora=lora_l, lora_scale=lora_scale), (k, v)

    xs = (params["layers"], jnp.arange(cfg.n_layers))
    if lora is not None:
        xs = xs + (lora,)
    x, (ks, vs) = jax.lax.scan(layer, x, xs)
    # ks/vs: [L, 1, C, Hkv, Dh] -> pages of `page_table`, offsets 0..C-1.
    slot = jnp.asarray(slot, jnp.int32)
    logical = jnp.arange(C)
    if _cache_is_q8(cache):
        kq8, kscale = quantize_kv(ks)
        vq8, vscale = quantize_kv(vs)
        cache = _paged_write(
            cache,
            {
                "k": kq8[:, 0], "v": vq8[:, 0],
                "k_scale": kscale[:, 0], "v_scale": vscale[:, 0],
            },
            page_table, logical,
        )
    else:
        cache = _paged_write(
            cache,
            {
                "k": ks[:, 0].astype(cache["k"].dtype),
                "v": vs[:, 0].astype(cache["v"].dtype),
            },
            page_table, logical,
        )
    cache["len"] = jax.lax.dynamic_update_slice(
        cache["len"], jnp.asarray(n_real, jnp.int32)[None], (slot,)
    )
    x_last = jax.lax.dynamic_slice(
        x, (0, jnp.asarray(n_real, jnp.int32) - 1, 0), (1, 1, x.shape[-1])
    )
    x_last = _rms_norm(x_last, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x_last, matmul_weight(params["out"], dt))
    return logits[:, 0].astype(jnp.float32), cache


def paged_extend_slot(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    slot: jax.Array,
    page_table: jax.Array,
    pos: jax.Array,
    n_real: jax.Array,
    lora: dict[str, tuple[jax.Array, jax.Array]] | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, KVCache]:
    """:func:`extend_slot` through a page table: continue row ``slot``
    with its next prompt chunk against the prefix its pages already
    hold. ``pos`` is the EXPLICIT continuation offset (the engine's
    host-tracked prefix length) rather than the stored ``len`` — that is
    what lets a radix prefix hit start a fresh occupant mid-row (the
    shared pages were written by an earlier request; the retired
    occupant's stale ``len`` means nothing). The row's logical view is
    gathered from its pages, run through :func:`decode_block` (the chunk
    attends prefix + itself — the speculative-verification math, exactly
    :func:`extend_slot`), and only the chunk's C new positions scatter
    back — shared prefix pages are READ, never written. ``len[slot]``
    becomes ``pos + n_real``. Returns position ``n_real - 1``'s logits
    ``[1, vocab]`` f32 and the cache.
    """
    slot = jnp.asarray(slot, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    n_real = jnp.asarray(n_real, jnp.int32)
    C = tokens.shape[0]
    row = _gather_paged(cache, page_table[None, :])  # [L, 1, V, ...]
    row["len"] = pos[None]
    logits, row = decode_block(
        params, tokens[None, :], row, cfg, lora=lora, lora_scale=lora_scale
    )
    logical = pos + jnp.arange(C)
    new = {
        key: jnp.take(row[key], logical, axis=2)[:, 0]
        for key in row
        if key != "len"
    }
    cache = _paged_write(cache, new, page_table, logical)
    cache["len"] = jax.lax.dynamic_update_slice(
        cache["len"], (pos + n_real)[None], (slot,)
    )
    last = jax.lax.dynamic_slice(
        logits, (0, n_real - 1, 0), (1, 1, logits.shape[-1])
    )
    return last[:, 0], cache


def paged_decode_step(
    params: Any,
    token: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    page_tables: jax.Array,
    lora: dict[str, tuple[jax.Array, jax.Array]] | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, KVCache]:
    """Pool-wide decode step through per-row page tables: gather every
    row's logical view ``[L, B, MP*ps, ...]`` from its pages, run the
    slot-pool :func:`decode_block` on it unchanged, and scatter each
    row's ONE new KV entry back to ``(page_tables[b, len[b]//ps],
    len[b]%ps)``. Rows whose table still points at the scratch page
    (free, or mid-prefill at a page boundary) write garbage there —
    never read, same visibility contract as the contiguous pool's idle
    rows. ``len`` advances by one for every row; the engine freezes idle
    rows' entries exactly as in contiguous mode. Logits are bitwise the
    contiguous :func:`decode_step`'s for the same logical contents.
    """
    pos0 = cache["len"]
    B = pos0.shape[0]
    ps = cache["k"].shape[2]
    view = _gather_paged(cache, page_tables)
    view["len"] = pos0
    logits, new_view = decode_block(
        params, token[:, None], view, cfg, lora=lora, lora_scale=lora_scale
    )
    pids = jnp.take_along_axis(page_tables, (pos0 // ps)[:, None], axis=1)[:, 0]
    offs = pos0 % ps
    out = dict(cache)
    for key, val in new_view.items():
        if key == "len":
            continue
        idx = pos0.reshape((1, B, 1) + (1,) * (val.ndim - 3))
        tok_kv = jnp.take_along_axis(val, idx, axis=2)[:, :, 0]  # [L, B, ...]
        out[key] = cache[key].at[:, pids, offs].set(tok_kv)
    out["len"] = pos0 + 1
    return logits[:, 0], out


def paged_verify_block(
    params: Any,
    block: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    page_tables: jax.Array,
    lora: dict[str, tuple[jax.Array, jax.Array]] | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, KVCache]:
    """Pool-wide T-token verify step through per-row page tables: the
    target-model half of the paged engine's speculative decode. ``block``
    ``[B, T]`` is each row's last verified token followed by its draft
    proposal; the row's logical view is gathered from its pages, the
    shared :func:`decode_block` scores every block position in ONE
    forward (logits at position ``t`` are bitwise what ``t`` sequential
    :func:`paged_decode_step` calls would produce — the greedy-accept
    comparison that makes speculative decoding lossless), and ALL ``T``
    new KV entries scatter back through the tables. The engine rewinds
    ``len`` past rejected positions afterwards — their stale KV sits
    beyond every later read's visibility mask and is overwritten in
    place when the row advances. Rows whose table points at the scratch
    page write garbage there, never read. Returns logits ``[B, T,
    vocab]`` f32 and the cache with ``len`` advanced by ``T`` (the
    engine freezes idle rows' entries, as in :func:`paged_decode_step`).
    """
    pos0 = cache["len"]
    B, T = block.shape
    ps = cache["k"].shape[2]
    view = _gather_paged(cache, page_tables)
    view["len"] = pos0
    logits, new_view = decode_block(
        params, block, view, cfg, lora=lora, lora_scale=lora_scale
    )
    logical = pos0[:, None] + jnp.arange(T)[None, :]  # [B, T]
    pids = jnp.take_along_axis(page_tables, logical // ps, axis=1)
    offs = logical % ps
    out = dict(cache)
    for key, val in new_view.items():
        if key == "len":
            continue
        idx = logical.reshape((1, B, T) + (1,) * (val.ndim - 3))
        tok_kv = jnp.take_along_axis(val, idx, axis=2)  # [L, B, T, ...]
        out[key] = cache[key].at[:, pids, offs].set(tok_kv)
    out["len"] = pos0 + T
    return logits, out


def _cache_is_q8(cache: KVCache) -> bool:
    return "k_scale" in cache


def _row_update(cache_rows: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Per-row cache insert: write ``new[b]`` into ``cache_rows[b]`` at
    row offset ``pos[b]`` (the slot-pool analog of the batch path's single
    scalar-offset ``dynamic_update_slice``). Starts clamp like
    ``dynamic_update_slice`` — callers bound ``pos + T <= Smax``."""

    def one(c, n, p):
        return jax.lax.dynamic_update_slice(c, n, (p,) + (0,) * (c.ndim - 1))

    return jax.vmap(one)(cache_rows, new, pos)


def _padded_prefill_attention(q, k, v, pad, attention: str = "auto"):
    """Prompt self-attention with per-row left padding.

    q: [B, T, H, Dh]; k, v: [B, T, Hkv, Dh]; pad: [B] leading pad counts.
    On TPU this stays on the flash kernel via its ``start`` input (pad
    keys masked in-kernel, O(T·Dh) HBM) — a serving-realistic 4-8k prompt
    through materialized-score attention would be exactly the quadratic
    HBM traffic the kernel exists to avoid. Off-TPU (or misfit shapes)
    it delegates to the shared grouped-attention math with an explicit
    key mask.
    """
    if use_flash(attention, q, None, kv_heads=k.shape[2]):
        return flash_attention(q, k, v, causal=True, start=pad)
    T = q.shape[1]
    live = jnp.arange(T)[None, :] >= pad[:, None]  # [B, Tk]
    return grouped_attention(
        q, k, v, causal=True, mask=jnp.broadcast_to(live[:, None, :], (q.shape[0], T, T))
    )


def prefill(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    pad: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling the cache.

    tokens: [B, Tp] -> (last-position logits [B, vocab], cache with
    ``len=Tp``). Prompt self-attention is the training attention path
    (flash on TPU); the cache is written, not read — prefill always starts
    a fresh sequence.

    ``pad`` ([B] leading pad counts) switches to LEFT-padded variable-
    length mode: RoPE positions are offset per row, pad keys are masked,
    and the last position holds every row's final real token.
    """
    dt = cfg.compute_dtype
    B, Tp = tokens.shape
    if pad is None:
        positions = jnp.arange(Tp)
    else:
        positions = jnp.clip(jnp.arange(Tp)[None, :] - pad[:, None], 0)
    x = embed_lookup(params["embed"], tokens, dt)

    def layer(x, xs):
        lp, _ = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        if pad is None:
            attn = flash_or_plain(
                q, k, v, attention=cfg.attention, causal=True, mesh=None
            )
        else:
            attn = _padded_prefill_attention(q, k, v, pad, cfg.attention)
        x = x + jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        return _mlp_block(x, lp, cfg), (k, v)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(cfg.n_layers))
    )
    # ks/vs: [L, B, Tp, Hkv, Dh] -> cache[:, :, :Tp]
    if _cache_is_q8(cache):
        kq8, kscale = quantize_kv(ks)
        vq8, vscale = quantize_kv(vs)
        cache = {
            "k": jax.lax.dynamic_update_slice(cache["k"], kq8, (0, 0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(cache["v"], vq8, (0, 0, 0, 0, 0)),
            "k_scale": jax.lax.dynamic_update_slice(
                cache["k_scale"], kscale, (0, 0, 0, 0)
            ),
            "v_scale": jax.lax.dynamic_update_slice(
                cache["v_scale"], vscale, (0, 0, 0, 0)
            ),
            "len": jnp.int32(Tp),
        }
    else:
        cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
            ),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
            ),
            "len": jnp.int32(Tp),
        }
    x = _rms_norm(x[:, -1:], params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, matmul_weight(params["out"], dt))
    return logits[:, 0].astype(jnp.float32), cache


def prefill_slot(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    slot: jax.Array,
    n_real: jax.Array,
) -> tuple[jax.Array, KVCache]:
    """Pack one request's opening prompt chunk into row ``slot`` of a
    slot-pool cache (:func:`init_slot_cache`) — the single-row prefill the
    continuous-batching engine runs when a freed slot admits a request.

    tokens: [C] RIGHT-padded chunk (static width C, so admission never
    retraces); ``n_real`` (traced scalar, 1..C) counts its real tokens;
    ``slot`` (traced scalar) picks the row. The chunk runs the training
    attention path causally — pads sit at the END, so real positions
    never see them and plain causal attention is already exact; the
    flash route additionally passes ``kv_len=n_real`` so pad KV blocks
    cost no MXU work (``workloads.attention.chunk_prefill_attention``).
    The row restarts at position 0: ``len[slot]`` becomes ``n_real``
    regardless of the retired occupant, and the stale KV beyond it is
    invisible by the visibility invariant (a cache position only becomes
    visible in the same step that overwrites it).

    Returns (last real position's logits [1, vocab] f32, cache) — the
    logits the engine samples the request's first token from, exactly
    :func:`prefill`'s last-position logits for the same prompt.
    """
    dt = cfg.compute_dtype
    C = tokens.shape[0]
    positions = jnp.arange(C)[None, :]
    x = embed_lookup(params["embed"], tokens[None, :], dt)  # [1, C, d]

    def layer(x, xs):
        lp, _ = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(h, lp, cfg, positions)
        attn = chunk_prefill_attention(q, k, v, n_real=n_real, attention=cfg.attention)
        x = x + jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        return _mlp_block(x, lp, cfg), (k, v)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (params["layers"], jnp.arange(cfg.n_layers))
    )
    # ks/vs: [L, 1, C, Hkv, Dh] -> row `slot`, offset 0.
    slot = jnp.asarray(slot, jnp.int32)
    if _cache_is_q8(cache):
        kq8, kscale = quantize_kv(ks)
        vq8, vscale = quantize_kv(vs)
        cache = {
            "k": jax.lax.dynamic_update_slice(cache["k"], kq8, (0, slot, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(cache["v"], vq8, (0, slot, 0, 0, 0)),
            "k_scale": jax.lax.dynamic_update_slice(
                cache["k_scale"], kscale, (0, slot, 0, 0)
            ),
            "v_scale": jax.lax.dynamic_update_slice(
                cache["v_scale"], vscale, (0, slot, 0, 0)
            ),
            "len": cache["len"],
        }
    else:
        cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], ks.astype(cache["k"].dtype), (0, slot, 0, 0, 0)
            ),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], vs.astype(cache["v"].dtype), (0, slot, 0, 0, 0)
            ),
            "len": cache["len"],
        }
    cache["len"] = jax.lax.dynamic_update_slice(
        cache["len"], jnp.asarray(n_real, jnp.int32)[None], (slot,)
    )
    # Last REAL position's logits (norm after the slice, like prefill).
    x_last = jax.lax.dynamic_slice(
        x, (0, jnp.asarray(n_real, jnp.int32) - 1, 0), (1, 1, x.shape[-1])
    )
    x_last = _rms_norm(x_last, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x_last, matmul_weight(params["out"], dt))
    return logits[:, 0].astype(jnp.float32), cache


def extend_slot(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    *,
    slot: jax.Array,
    n_real: jax.Array,
) -> tuple[jax.Array, KVCache]:
    """Continue a partially-prefilled slot row with its next prompt chunk
    (chunked prefill): run ``tokens`` ([C] right-padded, ``n_real`` real)
    through :func:`decode_block` against row ``slot``'s cache — the chunk
    attends the row's existing prefix plus itself, the exact
    speculative-verification math — then advance ``len[slot]`` by
    ``n_real`` only (the pad tail is written but stays invisible).

    The row is sliced out, processed as a [1, C] block, and written back,
    so the other slots' rows are untouched bytes — interleaving this
    between engine decode steps cannot perturb decoding neighbors.
    Returns (position ``n_real - 1``'s logits [1, vocab] f32 — the
    next-token logits when this is the prompt's final chunk, exactly what
    solo :func:`prefill` would return — and the updated cache).
    """
    slot = jnp.asarray(slot, jnp.int32)
    n_real = jnp.asarray(n_real, jnp.int32)
    row = {
        key: jax.lax.dynamic_slice_in_dim(val, slot, 1, axis=1)
        for key, val in cache.items()
        if key != "len"
    }
    pos = jax.lax.dynamic_slice(cache["len"], (slot,), (1,))  # [1] vector
    row["len"] = pos
    logits, row = decode_block(params, tokens[None, :], row, cfg)
    new = {
        key: jax.lax.dynamic_update_slice(
            cache[key], row[key], (0, slot) + (0,) * (cache[key].ndim - 2)
        )
        for key in cache
        if key != "len"
    }
    new["len"] = jax.lax.dynamic_update_slice(
        cache["len"], pos + n_real, (slot,)
    )
    last = jax.lax.dynamic_slice(
        logits, (0, n_real - 1, 0), (1, 1, logits.shape[-1])
    )
    return last[:, 0], new


def decode_step(
    params: Any,
    token: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    start: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """One cached decode step. token: [B] -> (logits [B, vocab], cache+1).

    ``start`` ([B] leading pad counts from a left-padded prefill) offsets
    each row's RoPE position and masks its pad slots out of attention.
    The T=1 case of :func:`decode_block` (single implementation of the
    cache-write/attention recipe).
    """
    logits, cache = decode_block(params, token[:, None], cache, cfg, start=start)
    return logits[:, 0], cache


def _mask_after_eos(gen: jax.Array, eos_id: int) -> jax.Array:
    """Overwrite positions strictly after each row's first EOS with EOS —
    the post-hoc equivalent of stopping (compiled loops always run their
    full static length; see module docstring). Shared by :func:`generate`
    and :func:`speculative_generate` so their outputs stay comparable."""
    seen = jnp.cumsum((gen == eos_id).astype(jnp.int32), axis=1)
    return jnp.where(seen - (gen == eos_id) > 0, eos_id, gen)


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Sample next tokens from ``[B, vocab]`` logits (compiled-friendly).

    ``temperature=0`` is greedy argmax (top_k/top_p ignored). Otherwise
    softmax sampling at the given temperature, optionally restricted to
    the ``top_k`` highest logits and/or the smallest set of tokens whose
    probability mass reaches ``top_p`` (nucleus). Both filters are static
    masks over sorted logits — no dynamic shapes, one compiled program.
    """
    # Validate before the greedy early-return: a bad sampler config must
    # fail at build time, not only once temperature is later enabled.
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        # Clamp to the vocab (sampler-config portability: top_k=50 on a
        # small-vocab model means "no truncation", not a trace error).
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep tokens while the mass BEFORE them is < top_p (the first
        # token is always kept); find the smallest kept logit.
        keep = (cum - probs) < top_p  # [B, vocab] over sorted order
        # smallest kept logit per row = min over kept sorted logits
        floor = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < floor, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def decode_block(
    params: Any,
    tokens: jax.Array,
    cache: KVCache,
    cfg: TransformerConfig,
    start: jax.Array | None = None,
    lora: dict[str, tuple[jax.Array, jax.Array]] | None = None,
    lora_scale: float = 1.0,
) -> tuple[jax.Array, KVCache]:
    """Cached decode of a T-token block: tokens [B, T] -> (logits
    [B, T, vocab] f32, cache advanced by T).

    ``lora`` (serving): :func:`lora_bgmv_views` output — per-slot
    layer-major adapter views ``{target: (a [L,B,fi,r], b [L,B,r,fo])}``
    that ride the layer scan as xs; every projection site adds its slot's
    gathered low-rank delta (``transformer._bgmv_delta``). Whether lora
    is passed is a Python-level (trace-time) property of the compiled
    program — the multi-LoRA engine ALWAYS passes it (null adapters for
    base slots), so adapter mix never retraces.

    Block position t attends to everything already in the cache plus
    block positions <= t; :func:`decode_step` is the T=1 case. One
    forward verifies a whole speculative draft — the target-model half
    of :func:`speculative_generate` — and the logits at every block
    position match what T sequential decode_step calls would produce
    (pinned by tests). ``start`` ([B] leading pad counts) offsets RoPE
    positions per row and masks pad slots, as in :func:`prefill`.

    With a slot-pool cache (``len`` a [B] vector, :func:`init_slot_cache`)
    every row advances from its OWN length: cache inserts land at per-row
    offsets, visibility and RoPE positions are per-row, and ``len`` grows
    per-row by T — the primitive under the continuous-batching engine's
    interleaved decode. Slot rows own their offsets outright (each starts
    at position 0), so ``start`` does not compose with slot mode.
    """
    dt = cfg.compute_dtype
    B, T = tokens.shape
    pos0 = cache["len"]
    per_slot = pos0.ndim == 1
    if per_slot and start is not None:
        raise ValueError(
            "start is the left-padded batch offset; slot-pool caches "
            "(vector len) already carry per-row offsets"
        )
    if per_slot:
        positions = pos0[:, None] + jnp.arange(T)[None, :]  # [B, T]
    else:
        positions = pos0 + jnp.arange(T)[None, :]  # [1, T] global positions
        if start is not None:
            positions = positions - start[:, None]  # [B, T] rope offsets
    positions = jnp.broadcast_to(positions, (B, T))
    x = embed_lookup(params["embed"], tokens, dt)  # [B, T, d]
    q8 = _cache_is_q8(cache)
    Smax = cache["k"].shape[2]
    idx = jnp.arange(Smax)
    # [B|1, T, Smax] visibility: cache prefix + block-causal, minus pads.
    if per_slot:
        vis = idx[None, None, :] < (
            pos0[:, None] + jnp.arange(T)[None, :] + 1
        )[:, :, None]
    else:
        vis = idx[None, None, :] < (pos0 + jnp.arange(T) + 1)[None, :, None]
        if start is not None:
            vis = vis & (idx[None, None, :] >= start[:, None, None])

    def layer(x, xs):
        lora_l = None
        if lora is not None:
            *xs, lora_l = xs
        if q8:
            lp, k_cache, v_cache, k_scale, v_scale = xs
        else:
            lp, k_cache, v_cache = xs
        h = _rms_norm(x, lp["ln1"])
        q, k, v = _project_qkv(
            h, lp, cfg, positions, lora=lora_l, lora_scale=lora_scale
        )
        if q8:
            kq8, ks_new = quantize_kv(k)
            vq8, vs_new = quantize_kv(v)
            if per_slot:
                k_cache = _row_update(k_cache, kq8, pos0)
                v_cache = _row_update(v_cache, vq8, pos0)
                k_scale = _row_update(k_scale, ks_new, pos0)
                v_scale = _row_update(v_scale, vs_new, pos0)
            else:
                k_cache = jax.lax.dynamic_update_slice(k_cache, kq8, (0, pos0, 0, 0))
                v_cache = jax.lax.dynamic_update_slice(v_cache, vq8, (0, pos0, 0, 0))
                k_scale = jax.lax.dynamic_update_slice(k_scale, ks_new, (0, pos0, 0))
                v_scale = jax.lax.dynamic_update_slice(v_scale, vs_new, (0, pos0, 0))
            k_mat = dequantize_kv(k_cache, k_scale, q.dtype)
            v_mat = dequantize_kv(v_cache, v_scale, q.dtype)
            carry = (k_cache, v_cache, k_scale, v_scale)
        elif per_slot:
            k_cache = _row_update(k_cache, k.astype(k_cache.dtype), pos0)
            v_cache = _row_update(v_cache, v.astype(v_cache.dtype), pos0)
            k_mat, v_mat = k_cache, v_cache
            carry = (k_cache, v_cache)
        else:
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, pos0, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, pos0, 0, 0)
            )
            k_mat, v_mat = k_cache, v_cache
            carry = (k_cache, v_cache)
        # Block-causal attention over the cache: the shared grouped-
        # attention math (rectangular q/k, explicit mask, dead-row zero
        # guard — one implementation repo-wide) with `vis` as the mask.
        attn = grouped_attention(
            q, k_mat, v_mat, causal=False,
            mask=jnp.broadcast_to(vis, (B, T, Smax)),
        )
        wo = jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt))
        wo_delta = _lora_wo_delta(attn, lora_l, lora_scale, dt)
        if wo_delta is not None:
            wo = wo + wo_delta
        x = x + wo
        return _mlp_block(x, lp, cfg, lora=lora_l, lora_scale=lora_scale), carry

    if q8:
        xs = (
            params["layers"], cache["k"], cache["v"],
            cache["k_scale"], cache["v_scale"],
        )
    else:
        xs = (params["layers"], cache["k"], cache["v"])
    if lora is not None:
        xs = xs + (lora,)
    if q8:
        x, (ks, vs, kss, vss) = jax.lax.scan(layer, x, xs)
        cache = {
            "k": ks, "v": vs, "k_scale": kss, "v_scale": vss, "len": pos0 + T,
        }
    else:
        x, (ks, vs) = jax.lax.scan(layer, x, xs)
        cache = {"k": ks, "v": vs, "len": pos0 + T}
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, matmul_weight(params["out"], dt))
    return logits.astype(jnp.float32), cache


def generate(
    params: Any,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    eos_id: int | None = None,
    prompt_lens: jax.Array | None = None,
    kv_dtype: str | None = None,
) -> jax.Array:
    """Generate ``max_new`` tokens after ``prompt`` ([B, Tp] int32).

    Returns [B, Tp + max_new]; with ``prompt_lens`` (variable-length
    batch), returns ONLY the generated block [B, max_new] — row i's
    tokens logically continue from position ``prompt_lens[i]``, so a
    concatenated layout would be ragged. ``prompt`` is right-padded as
    given; it is re-packed LEFT-padded internally so every row's decode
    writes the same cache slot (static shapes, no per-row scatter).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (``rng`` required), optionally truncated by
    ``top_k`` and/or nucleus ``top_p`` (:func:`sample_logits`). With
    ``eos_id``, positions after the first EOS are overwritten with EOS
    (post-hoc mask — the compiled loop always runs ``max_new`` steps;
    see module docstring).

    Wrap in ``jax.jit`` via :func:`make_generate` for repeated use.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs rng")
    B, Tp = prompt.shape
    cache = init_cache(cfg, B, Tp + max_new, kv_dtype=kv_dtype)
    pad = None
    if prompt_lens is not None:
        pad = (Tp - prompt_lens).astype(jnp.int32)
        # right-padded -> left-padded: roll each row by its pad count
        prompt_packed = jax.vmap(jnp.roll)(prompt, pad)
        logits, cache = prefill(params, prompt_packed, cache, cfg, pad=pad)
    else:
        logits, cache = prefill(params, prompt, cache, cfg)
    rng = rng if rng is not None else jax.random.key(0)

    def pick(logits, key):
        return sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )

    rng, k0 = jax.random.split(rng)
    first = pick(logits, k0).astype(jnp.int32)  # [B]

    def step(carry, _):
        token, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = decode_step(params, token, cache, cfg, start=pad)
        nxt = pick(logits, sub).astype(jnp.int32)
        return (nxt, cache, key), token

    (_last, cache, _), toks = jax.lax.scan(
        step, (first, cache, rng), None, length=max_new
    )
    gen = toks.T  # [B, max_new]
    if eos_id is not None:
        gen = _mask_after_eos(gen, eos_id)
    if prompt_lens is not None:
        return gen
    return jnp.concatenate([prompt, gen], axis=1)  # [B, Tp + max_new]


def speculative_generate(
    target_params: Any,
    draft_params: Any,
    prompt: jax.Array,
    target_cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    *,
    max_new: int,
    k: int = 4,
    eos_id: int | None = None,
    return_stats: bool = False,
):
    """Greedy speculative decoding: the draft model proposes ``k`` tokens
    per round, the target verifies them in ONE :func:`decode_block`
    forward, and the longest matching prefix plus the target's correction
    token are emitted. Output is the target model's greedy continuation —
    exact by construction (pinned by tests at f32; in bf16 a near-tied
    argmax can in principle round differently between the block and
    per-step einsum shapes, in which case the output is still a valid
    greedy continuation of the target at that tolerance). The draft only
    changes how many target forwards it takes: ~``max_new/(accepted+1)``
    instead of ``max_new``. At small batch the decode wall is the
    target's weight stream (see docs/serving.md), so acceptance ~= speedup.

    Single-sequence scope (``B == 1``): rows accepting different prefix
    lengths would need per-row cache lengths; the latency-bound serving
    case this targets is batch 1. Cache ``len`` rewinds past rejected
    draft entries each round — stale cache positions are masked by
    construction. Both configs must share a vocab.

    Returns ``[1, Tp + max_new]`` like greedy :func:`generate`; with
    ``return_stats=True`` returns ``(tokens, {"rounds", "drafted",
    "accepted"})`` — acceptance telemetry, and the observable that pins
    the draft-cache bookkeeping (a perfect draft must finish in
    ``ceil((max_new-1)/(k+1))`` rounds; a stale/unwritten cache slot
    would show up as extra rounds, invisible in the tokens).
    """
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"target/draft vocab mismatch: {target_cfg.vocab} vs {draft_cfg.vocab}"
        )
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    B, Tp = prompt.shape
    if B != 1:
        raise ValueError(f"speculative_generate is single-sequence; got B={B}")

    width = max_new + k + 1  # out buffer: last round may overhang by <= k
    t_cache = init_cache(target_cfg, B, Tp + width)
    d_cache = init_cache(draft_cfg, B, Tp + width)
    t_logits, t_cache = prefill(target_params, prompt, t_cache, target_cfg)
    _, d_cache = prefill(draft_params, prompt, d_cache, draft_cfg)
    first = jnp.argmax(t_logits, -1).astype(jnp.int32)  # [1]
    out = jnp.zeros((B, width), jnp.int32)
    out = out.at[:, 0].set(first)

    def cond(carry):
        _, n, *_ = carry
        return n < max_new

    def body(carry):
        out, n, last, t_cache, d_cache, stats = carry

        # Draft proposes k greedy tokens from `last`. The scan runs k+1
        # steps: the extra step consumes drafts[k-1] so its KV is written
        # — on full acceptance the rewind marks that slot valid, and an
        # unwritten (zero) entry there would silently poison every later
        # draft prediction (acceptance collapses while output stays
        # correct). Its proposal is discarded.
        def d_step(cs, _):
            c, tok = cs
            logits, c2 = decode_step(draft_params, tok, c, draft_cfg)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return (c2, nxt), nxt

        (d_cache, _), proposals = jax.lax.scan(
            d_step, (d_cache, last), None, length=k + 1
        )
        drafts = proposals[:k].T  # [k, 1] -> [1, k]

        # Target verifies the whole draft in one block forward.
        block = jnp.concatenate([last[:, None], drafts], axis=1)  # [1, k+1]
        logits, t_cache = decode_block(target_params, block, t_cache, target_cfg)
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)  # [1, k+1]

        # Longest matching prefix a, then emit drafts[:a] + greedy[a].
        match = (drafts == greedy[:, :k]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)[0]  # scalar
        d_pad = jnp.concatenate([drafts, jnp.zeros((1, 1), jnp.int32)], axis=1)
        correction = jnp.take_along_axis(greedy, a[None, None], axis=1)  # [1,1]
        emit = jnp.where(jnp.arange(k + 1)[None] < a, d_pad, correction)
        out = jax.lax.dynamic_update_slice(out, emit, (0, n))

        emitted = a + 1
        n2 = n + emitted
        # Rewind cache lens past rejected entries: the valid prefix is the
        # emitted sequence up to (not including) the new `last` token.
        t_cache = {**t_cache, "len": jnp.int32(Tp) + n2 - 1}
        d_cache = {**d_cache, "len": jnp.int32(Tp) + n2 - 1}
        last = correction[:, 0]
        stats = {
            "rounds": stats["rounds"] + 1,
            "drafted": stats["drafted"] + k,
            "accepted": stats["accepted"] + a,
        }
        return out, n2, last, t_cache, d_cache, stats

    zero_stats = {
        "rounds": jnp.int32(0), "drafted": jnp.int32(0), "accepted": jnp.int32(0),
    }
    out, n, last, _, _, stats = jax.lax.while_loop(
        cond, body, (out, jnp.int32(1), first, t_cache, d_cache, zero_stats)
    )
    gen = out[:, :max_new]
    if eos_id is not None:
        gen = _mask_after_eos(gen, eos_id)
    tokens = jnp.concatenate([prompt, gen], axis=1)
    if return_stats:
        return tokens, stats
    return tokens


def make_speculative_generate(
    target_cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    *,
    max_new: int,
    k: int = 4,
    eos_id: int | None = None,
    return_stats: bool = False,
):
    """Jitted closure: (target_params, draft_params, prompt) ->
    [1, Tp + max_new] (or (tokens, stats) with ``return_stats``)."""
    fn = functools.partial(
        speculative_generate, max_new=max_new, k=k, eos_id=eos_id,
        return_stats=return_stats,
    )
    return jax.jit(
        lambda tp, dp, prompt: fn(tp, dp, prompt, target_cfg, draft_cfg)
    )


def make_generate(
    cfg: TransformerConfig,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    padded: bool = False,
    kv_dtype: str | None = None,
):
    """Jitted generate closure (one compile per prompt shape).

    ``padded=False``: (params, prompt, rng) -> [B, Tp+max_new].
    ``padded=True``: (params, prompt, prompt_lens, rng) -> [B, max_new]
    (the variable-length serving path). ``kv_dtype="int8"`` serves from a
    half-size quantized KV cache (see :func:`init_cache`); sampling
    controls per :func:`sample_logits`.
    """
    fn = functools.partial(
        generate, cfg=cfg, max_new=max_new, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_id=eos_id, kv_dtype=kv_dtype,
    )
    if padded:
        return jax.jit(
            lambda params, prompt, prompt_lens, rng: fn(
                params, prompt, rng=rng, prompt_lens=prompt_lens
            )
        )
    return jax.jit(lambda params, prompt, rng: fn(params, prompt, rng=rng))
