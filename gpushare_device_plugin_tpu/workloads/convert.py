"""Checkpoint conversion: HF-Llama-style state dicts <-> this repo's tree.

A user arriving from the standard ecosystem has per-layer weights named
``model.layers.{i}.self_attn.q_proj.weight`` etc. (each a 2-D
``[out_features, in_features]`` matrix, torch convention); this repo's
decoder stores stacked-over-layers einsum-shaped arrays
(``transformer.init_params``: ``wq [L, d, H, Dh]``, ``wkv [L, d, 2, Hkv,
Dh]``, ...). The mapping is pure reshapes/transposes — no numerics —
and is verified by a round-trip test against the exact inverse.

Scope: the Llama decoder family (what ``TransformerConfig`` models —
RMSNorm, RoPE, SwiGLU, GQA, untied lm_head). Inputs are plain
name->array mappings (numpy or jax arrays); torch tensors should be
converted with ``.numpy()`` first — this module never imports torch.

Note on RoPE conventions: this repo rotates (x[:half], x[half:]) pairs —
the same "rotate_half" layout HF's modeling code uses — so projection
weights map 1:1 with no permutation.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig

Params = dict[str, Any]


def _hf_names(i: int) -> dict[str, str]:
    p = f"model.layers.{i}."
    return {
        "wq": p + "self_attn.q_proj.weight",
        "wk": p + "self_attn.k_proj.weight",
        "wv": p + "self_attn.v_proj.weight",
        "wo": p + "self_attn.o_proj.weight",
        "wgate": p + "mlp.gate_proj.weight",
        "wup": p + "mlp.up_proj.weight",
        "wdown": p + "mlp.down_proj.weight",
        "ln1": p + "input_layernorm.weight",
        "ln2": p + "post_attention_layernorm.weight",
    }


def from_hf_llama(
    state: Mapping[str, Any], cfg: TransformerConfig
) -> Params:
    """HF-Llama name->array mapping -> ``init_params``-shaped tree (f32).

    Expects the standard keys (``model.embed_tokens.weight``,
    ``model.layers.{i}.*``, ``model.norm.weight``, ``lm_head.weight``)
    with torch ``[out, in]`` matrix convention. Raises KeyError with the
    missing name if the state dict doesn't match ``cfg``'s layer count.
    """
    d, H, Dh, Hkv, F, L = (
        cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.kv_heads,
        cfg.d_ff, cfg.n_layers,
    )

    def arr(name):
        return jnp.asarray(np.asarray(state[name]), jnp.float32)

    layers: dict[str, list] = {k: [] for k in (
        "wq", "wkv", "wo", "wi", "wdown", "ln1", "ln2"
    )}
    for i in range(L):
        n = _hf_names(i)
        # q_proj [H*Dh, d] -> [d, H, Dh]
        layers["wq"].append(arr(n["wq"]).reshape(H, Dh, d).transpose(2, 0, 1))
        # k/v [Hkv*Dh, d] -> stacked [d, 2, Hkv, Dh]
        wk = arr(n["wk"]).reshape(Hkv, Dh, d).transpose(2, 0, 1)
        wv = arr(n["wv"]).reshape(Hkv, Dh, d).transpose(2, 0, 1)
        layers["wkv"].append(jnp.stack([wk, wv], axis=1))
        # o_proj [d, H*Dh] -> [H, Dh, d]
        layers["wo"].append(arr(n["wo"]).reshape(d, H, Dh).transpose(1, 2, 0))
        # gate/up [F, d] -> stacked [d, 2, F]
        wg = arr(n["wgate"]).T  # [d, F]
        wu = arr(n["wup"]).T
        layers["wi"].append(jnp.stack([wg, wu], axis=1))
        # down [d, F] -> [F, d]
        layers["wdown"].append(arr(n["wdown"]).T)
        layers["ln1"].append(arr(n["ln1"]))
        layers["ln2"].append(arr(n["ln2"]))

    return {
        "embed": arr("model.embed_tokens.weight"),  # [V, d]
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
        "final_norm": arr("model.norm.weight"),
        "out": arr("lm_head.weight").T,  # [V, d] -> [d, V]
    }


def to_hf_llama(params: Params, cfg: TransformerConfig) -> dict[str, np.ndarray]:
    """Exact inverse of :func:`from_hf_llama` (numpy outputs) — exporting
    a trained/merged tree back to the standard layout, and the round-trip
    oracle for the import test."""
    d, H, Dh, Hkv, F, L = (
        cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.kv_heads,
        cfg.d_ff, cfg.n_layers,
    )
    lp = params["layers"]
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
        "lm_head.weight": np.asarray(params["out"], np.float32).T,
    }
    for i in range(L):
        n = _hf_names(i)
        out[n["wq"]] = np.asarray(
            jnp.transpose(lp["wq"][i], (1, 2, 0)).reshape(H * Dh, d), np.float32
        )
        out[n["wk"]] = np.asarray(
            jnp.transpose(lp["wkv"][i, :, 0], (1, 2, 0)).reshape(Hkv * Dh, d),
            np.float32,
        )
        out[n["wv"]] = np.asarray(
            jnp.transpose(lp["wkv"][i, :, 1], (1, 2, 0)).reshape(Hkv * Dh, d),
            np.float32,
        )
        out[n["wo"]] = np.asarray(
            jnp.transpose(lp["wo"][i], (2, 0, 1)).reshape(d, H * Dh), np.float32
        )
        out[n["wgate"]] = np.asarray(lp["wi"][i, :, 0], np.float32).T
        out[n["wup"]] = np.asarray(lp["wi"][i, :, 1], np.float32).T
        out[n["wdown"]] = np.asarray(lp["wdown"][i], np.float32).T
        out[n["ln1"]] = np.asarray(lp["ln1"][i], np.float32)
        out[n["ln2"]] = np.asarray(lp["ln2"][i], np.float32)
    return out
