"""LoRA adapters for the decoder: low-rank fine-tuning sized to a slice.

Fine-tuning the full 0.5B-param demo decoder needs ~6 GB of f32 masters
plus Adam moments; a fractional-HBM pod on a 2-4 GiB slice cannot hold
that. LoRA trains rank-r deltas instead: per target weight ``W`` a pair
``A [in, r]``, ``B [r, out]`` with ``W' = W + (alpha/r) * A @ B`` —
optimizer state shrinks from the full model to the adapters (MBs), the
frozen base can stay bf16 (or int8), and the trained artifact is small
enough to checkpoint and ship per task.

Design (functional, matching the repo's param-tree style):

- Adapters are a pytree parallel to ``params["layers"]``, stacked over
  the layer dim like every other weight (``lax.scan`` compatibility).
- ``B`` initializes to zeros, so step 0 is exactly the base model —
  the standard LoRA guarantee, pinned by tests.
- Training merges under jit (``merge_lora`` is einsum + add; XLA fuses,
  and the merged tree is a transient — the optimizer only ever sees
  adapter-sized state). Serving either merges once up front (then
  optionally quantizes: LoRA + int8 compose) or ships the merged tree.

Reference parity note: the reference has no training stack at all
(SURVEY.md section 2); this extends the workload half beyond parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from .transformer import TransformerConfig, loss_fn

Params = dict[str, Any]

# target -> (A shape suffix (contraction side), B shape suffix (output
# side)) relative to the stacked [L, ...] layer weights of init_params.
_TARGET_SHAPES = {
    "wq": (("d",), ("H", "Dh")),
    "wkv": (("d",), ("two", "Hkv", "Dh")),
    "wo": (("H", "Dh"), ("d",)),
    "wi": (("d",), ("two", "F")),
    "wdown": (("F",), ("d",)),
}


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # Which layer projections get adapters. Attention-only by default
    # (the standard recipe); any subset of _TARGET_SHAPES works.
    targets: tuple[str, ...] = ("wq", "wo")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _dims(cfg: TransformerConfig) -> dict[str, int]:
    return {
        "d": cfg.d_model, "H": cfg.n_heads, "Dh": cfg.head_dim,
        "Hkv": cfg.kv_heads, "F": cfg.d_ff, "two": 2,
    }


def init_lora(
    rng: jax.Array, cfg: TransformerConfig, lcfg: LoraConfig
) -> Params:
    """Adapter tree: {target: {"a": [L, *in, r], "b": [L, r, *out]}}.

    ``a`` gets the fan-in-scaled normal init, ``b`` zeros — the merged
    model starts exactly at the base weights.
    """
    dims = _dims(cfg)
    L, r = cfg.n_layers, lcfg.rank
    if r < 1:
        raise ValueError(f"rank must be >= 1, got {r}")
    if len(set(lcfg.targets)) != len(lcfg.targets):
        raise ValueError(f"duplicate LoRA targets in {lcfg.targets}")
    out = {}
    keys = jax.random.split(rng, len(lcfg.targets))
    for key, name in zip(keys, lcfg.targets):
        if name not in _TARGET_SHAPES:
            raise ValueError(
                f"unknown LoRA target {name!r}: expected one of "
                f"{sorted(_TARGET_SHAPES)}"
            )
        in_names, out_names = _TARGET_SHAPES[name]
        in_shape = tuple(dims[n] for n in in_names)
        out_shape = tuple(dims[n] for n in out_names)
        fan_in = 1
        for s in in_shape:
            fan_in *= s
        out[name] = {
            "a": (
                jax.random.normal(key, (L, *in_shape, r)) / jnp.sqrt(fan_in)
            ).astype(jnp.float32),
            "b": jnp.zeros((L, r, *out_shape), jnp.float32),
        }
    return out


def _delta(a: jax.Array, b: jax.Array, scale: float) -> jax.Array:
    """(alpha/r) * A @ B over the rank dim, preserving the [L, *in, *out]
    layout of the stacked base weight."""
    L = a.shape[0]
    r = a.shape[-1]
    a2 = a.reshape(L, -1, r)  # [L, in, r]
    b2 = b.reshape(L, r, -1)  # [L, r, out]
    d = jnp.einsum("lir,lro->lio", a2, b2) * scale
    return d.reshape(*a.shape[:-1], *b.shape[2:])


def merge_lora(params: Params, lora: Params, lcfg: LoraConfig) -> Params:
    """Base params + adapter deltas (targets only; everything else is the
    same array, not a copy). The result drops into every existing entry
    point — forward, generate, quantize_decoder."""
    layers = dict(params["layers"])
    for name, ab in lora.items():
        w = layers[name]
        layers[name] = (w.astype(jnp.float32) + _delta(
            ab["a"], ab["b"], lcfg.scale
        )).astype(w.dtype)
    return {**params, "layers": layers}


def lora_loss_fn(
    lora: Params,
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    lcfg: LoraConfig,
    mesh=None,
) -> jax.Array:
    """Next-token loss of the merged model, differentiable in ``lora``
    only (``params`` rides through without gradient)."""
    merged = merge_lora(jax.lax.stop_gradient(params), lora, lcfg)
    return loss_fn(merged, tokens, cfg, mesh)


def make_lora_train_step(
    mesh, cfg: TransformerConfig, lcfg: LoraConfig, optimizer=None,
    lr: float = 1e-3,
):
    """(step, init_opt_state) pair for adapter-only training.

    ``step(params, lora, opt_state, tokens) -> (lora, opt_state, loss)``;
    ``init_opt_state(lora)`` builds the matching optimizer state. They
    are returned TOGETHER so a custom ``optimizer`` can never be paired
    with a mismatched init (an optax pytree-structure error deep in jit).

    The base ``params`` are frozen (never donated, never updated) and the
    optimizer state covers only the adapters — the whole point: full
    fine-tuning quality-ish at adapter-sized optimizer memory.
    """
    from .optim import make_optimizer

    opt = optimizer or make_optimizer(lr)

    def step(params, lora, opt_state, tokens):
        loss, grads = jax.value_and_grad(lora_loss_fn)(
            lora, params, tokens, cfg, lcfg, mesh
        )
        updates, opt_state = opt.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return lora, opt_state, loss

    return jax.jit(step, donate_argnums=(1, 2)), opt.init


def lora_param_count(lora: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(lora))


# --- canonical flat layout (paged adapter serving) --------------------------
# The multi-LoRA serving path (serving/adapters.py + generate.py's BGMV
# hooks) stores each adapter as ONE flat f32 vector striped across KV-pool
# pages. The layout below is the contract between the host-side loader
# (flatten) and the in-kernel gather (unflatten): targets in lcfg.targets
# order, each target's ``a`` then ``b``, raveled in C order — exactly the
# element order ``_delta``'s reshape sees, so the unflattened views feed
# the same low-rank contraction ``merge_lora`` bakes into the weights.


def _target_flat_dims(
    cfg: TransformerConfig, name: str
) -> tuple[int, int]:
    """(fan-in, fan-out) of one target's projection, flattened."""
    dims = _dims(cfg)
    in_names, out_names = _TARGET_SHAPES[name]
    fi = 1
    for n in in_names:
        fi *= dims[n]
    fo = 1
    for n in out_names:
        fo *= dims[n]
    return fi, fo


def lora_flat_len(cfg: TransformerConfig, lcfg: LoraConfig) -> int:
    """Float count of one adapter in the canonical flat layout."""
    L, r = cfg.n_layers, lcfg.rank
    total = 0
    for name in lcfg.targets:
        fi, fo = _target_flat_dims(cfg, name)
        total += L * fi * r + L * r * fo
    return total


def flatten_lora(
    lora: Params, cfg: TransformerConfig, lcfg: LoraConfig
) -> jax.Array:
    """One adapter tree -> the canonical flat f32 vector
    (``[lora_flat_len]``); raises on a tree missing a configured target
    (a half-loaded adapter must fail at load, not decode garbage)."""
    parts = []
    for name in lcfg.targets:
        try:
            ab = lora[name]
        except KeyError:
            raise ValueError(
                f"adapter tree has no {name!r} entry but lcfg.targets="
                f"{lcfg.targets}"
            ) from None
        parts.append(ab["a"].astype(jnp.float32).reshape(-1))
        parts.append(ab["b"].astype(jnp.float32).reshape(-1))
    return jnp.concatenate(parts)


def unflatten_lora(
    flat: jax.Array, cfg: TransformerConfig, lcfg: LoraConfig
) -> dict[str, tuple[jax.Array, jax.Array]]:
    """Flat vector(s) -> {target: (a [..., L, in, r], b [..., L, r, out])}
    with the in/out dims FLATTENED (the shape ``_delta`` contracts over).
    Works batched: any leading dims of ``flat`` ride through, so a
    gathered per-slot ``[B, F]`` slab read yields per-slot views."""
    L, r = cfg.n_layers, lcfg.rank
    lead = flat.shape[:-1]
    out: dict[str, tuple[jax.Array, jax.Array]] = {}
    off = 0
    for name in lcfg.targets:
        fi, fo = _target_flat_dims(cfg, name)
        na, nb = L * fi * r, L * r * fo
        a = flat[..., off:off + na].reshape(*lead, L, fi, r)
        off += na
        b = flat[..., off:off + nb].reshape(*lead, L, r, fo)
        off += nb
        out[name] = (a, b)
    return out
