"""Shared optimizer construction for the demo workloads.

One place for the training hygiene every real run wants — global-norm
gradient clipping and a warmup-cosine learning-rate schedule — so the
per-model ``make_optimizer`` helpers stay one-liners and cannot drift.
Pure optax composition; everything jit-traces into the train step.
"""

from __future__ import annotations

import optax


def make_optimizer(
    lr: float = 3e-4,
    *,
    weight_decay: float = 0.01,
    clip_norm: float | None = None,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    min_lr_ratio: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.999,
) -> optax.GradientTransformation:
    """AdamW with opt-in global-norm clipping and warmup-cosine decay.

    Defaults produce EXACTLY ``optax.adamw(lr, weight_decay=...)`` — same
    hyperparameters AND the same opt-state pytree (no wrapping chain) —
    because the opt-state structure is a checkpoint compatibility
    contract: orbax restore of a run saved before this module existed
    must keep working (``trainer.py``'s resume-after-eviction promise).

    - ``clip_norm=1.0`` is the standard LLM clipping setting (opt-in; it
      nests the opt state one chain level deeper).
    - With ``total_steps``, the LR warms up linearly over ``warmup_steps``
      then follows a cosine decay to ``lr * min_lr_ratio``; without it the
      LR is constant. NOTE: a schedule also changes the opt-state pytree
      (optax swaps ``scale()`` for ``scale_by_schedule()``, which carries
      a step counter) — like clipping, turning it on/off across a restart
      is a checkpoint-structure change.
    """
    if warmup_steps and total_steps is None:
        raise ValueError(
            "warmup_steps requires total_steps (otherwise the LR would "
            "silently stay constant at full peak)"
        )
    if total_steps is not None:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=max(1, warmup_steps),
            decay_steps=total_steps,
            end_value=lr * min_lr_ratio,
        )
    else:
        schedule = lr
    adamw = optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay)
    if clip_norm is None:
        return adamw
    return optax.chain(optax.clip_by_global_norm(clip_norm), adamw)
