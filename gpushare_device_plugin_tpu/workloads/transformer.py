"""Flagship workload: Llama-style decoder LM, sharded TPU-first.

Design notes (TPU/XLA):
- **scan over layers** — one compiled layer body, `lax.scan` over stacked
  layer params: compile time independent of depth, XLA pipelines the MXU
  matmuls.
- **remat** — the scan body is wrapped in `jax.checkpoint`, trading FLOPs
  for HBM (essential for fractional-HBM pods whose XLA client is capped by
  the plugin's cooperative limit, ``parallel/podenv.py``).
- **sharding** — params carry NamedShardings over the (dp, fsdp, tp, sp)
  mesh (``parallel/mesh.py``); activations get
  `with_sharding_constraint`; XLA inserts all collectives. fsdp is
  ZeRO-style: param dims shard over ``fsdp`` and the batch shards over
  ``(dp, fsdp)``.
- **long context** — `seq_parallel=True` switches attention to the ring
  implementation (``parallel/ring.py``), sequence sharded over ``sp``.
- **bfloat16 compute** — params are kept f32 (optimizer quality), cast to
  ``cfg.compute_dtype`` for the matmuls so they land on the MXU in bf16.

The reference has no model code (SURVEY.md section 2); this is the workload
half the TPU framework adds.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import batch_sharding, commit_to_mesh, prune_unshardable
from ..parallel.ring import ring_attention
from ..parallel.ulysses import ulysses_attention
from .attention import flash_or_plain, ulysses_inner_attn
from .quant import embed_lookup, matmul_weight

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    # Grouped-query attention: n_kv_heads < n_heads shares each KV head
    # across n_heads/n_kv_heads query heads (Llama-3 style). None = MHA.
    n_kv_heads: int | None = None
    d_ff: int = 352
    max_seq: int = 256
    rope_theta: float = 10000.0
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    # Remat granularity when ``remat`` is on:
    # - "full": save only layer inputs; the backward re-runs each layer's
    #   whole forward (cheapest HBM, ~4/3 the model FLOPs — an MFU
    #   measured against 3x-forward is capped at 75%).
    # - "dots": save an explicit allowlist of named projection outputs
    #   (post-RoPE q/k/v, the attention output, the wo projection, the
    #   MLP gate/up — see the checkpoint_name calls below); the backward
    #   recomputes only cheap elementwise ops (norms, RoPE's linear
    #   rotation, silu, residual adds), so compute stays ~3x forward at
    #   O(saved projections) activation HBM. The
    #   allowlist deliberately excludes attention scores, so plain
    #   attention never checkpoints an [S, S] matrix under this policy.
    #   The right choice whenever the activations fit — fractional-HBM
    #   pods keep "full".
    remat_policy: str = "full"
    seq_parallel: bool = False
    # Context-parallel scheme when seq_parallel: "ring" (K/V ppermute ring
    # with overlappable hops; flash-kernel hops on TPU when local blocks
    # fit) or "ulysses" (two all_to_all swaps to a full-sequence layout,
    # one whole-S kernel per shard). Both exact; parallel/ulysses.py has
    # the trade.
    context_parallel: str = "ring"
    # "auto": the Pallas flash kernel (ops/flash_attention.py) on TPU, plain
    # attention elsewhere (the kernel's CPU fallback is the Pallas
    # interpreter — correct but far too slow for training loops).
    # "flash" / "plain" force one path.
    attention: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        if self.n_heads % kv:
            raise ValueError(f"n_heads={self.n_heads} not divisible by n_kv_heads={kv}")
        return kv


def llama3_8b() -> TransformerConfig:
    """The Llama-3-8B shape (BASELINE.md config 4's v4-32 FSDP workload)."""
    return TransformerConfig(
        vocab=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=8192, rope_theta=500000.0,
    )


# --- init -------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    k_embed, k_layers, k_out = jax.random.split(rng, 3)
    d, H, Dh, F, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers
    Hkv = cfg.kv_heads

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape) / jnp.sqrt(fan_in)).astype(jnp.float32)

    ks = jax.random.split(k_layers, 5)
    return {
        "embed": norm(k_embed, (cfg.vocab, d), d),
        "layers": {
            # stacked on leading L for lax.scan
            "wq": norm(ks[0], (L, d, H, Dh), d),
            "wkv": norm(ks[4], (L, d, 2, Hkv, Dh), d),  # [k, v] grouped heads
            "wo": norm(ks[1], (L, H, Dh, d), d),
            "wi": norm(ks[2], (L, d, 2, F), d),  # [gate, up]
            "wdown": norm(ks[3], (L, F, d), F),
            "ln1": jnp.ones((L, d), jnp.float32),
            "ln2": jnp.ones((L, d), jnp.float32),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "out": norm(k_out, (d, cfg.vocab), d),
    }


def param_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpec pytree matching :func:`init_params`.

    tp shards heads / mlp-hidden / vocab; fsdp shards the model dim
    (ZeRO-style — XLA all-gathers per layer under the scan).
    """
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "wq": P(None, "fsdp", "tp", None),
            "wkv": P(None, "fsdp", None, "tp", None),
            "wo": P(None, "tp", None, "fsdp"),
            "wi": P(None, "fsdp", None, "tp"),
            "wdown": P(None, "tp", "fsdp"),
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "final_norm": P(None),
        "out": P("fsdp", "tp"),
    }


def param_shardings(mesh: Mesh, cfg: TransformerConfig) -> Params:
    abstract = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    specs = prune_unshardable(param_specs(cfg), abstract, mesh)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, mesh: Mesh, cfg: TransformerConfig) -> Params:
    return jax.device_put(params, param_shardings(mesh, cfg))


# --- model ------------------------------------------------------------------

def _rms_norm(x, weight, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, T, H, Dh]; positions: [T] shared or [B, T] per-row token
    positions (padded generation offsets positions per row)."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [*, T, half]
    if angles.ndim == 2:  # shared positions -> add the batch dim
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)  # [B|1, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _bgmv_delta(x, a, b, scale: float, dt) -> jax.Array:
    """Per-slot low-rank delta (gathered BGMV): ``(x @ A) @ B * scale``.

    x: [B, T, fi]; a: [B, fi, r]; b: [B, r, fo] -> [B, T, fo]. Every slot
    contracts against ITS OWN adapter pair, so a batch mixing arbitrary
    adapters is still one einsum — adapter identity is data (the gathered
    a/b values), never a shape, preserving the zero-retrace invariant.
    A null slot (a/b gathered from the zero scratch page) contributes an
    exactly-zero delta, so base-model requests ride the same dispatch.
    """
    xa = jnp.einsum("btd,bdr->btr", x, a.astype(dt))
    return jnp.einsum("btr,bro->bto", xa, b.astype(dt)) * scale


def _project_qkv(h, lp, cfg: TransformerConfig, positions, lora=None,
                 lora_scale: float = 1.0):
    """ln1-normalized hidden -> RoPE'd (q [B,T,H,Dh], k, v [B,T,Hkv,Dh]).

    Shared by the training forward and the cached decode path
    (``generate.py``) so the layer math exists exactly once — cached
    decode's contract is token-exactness with this forward.

    ``lora`` (serving only): {target: (a [B,fi,r], b [B,r,fo])} per-slot
    adapter views for THIS layer; deltas are added to the raw projections
    BEFORE RoPE — the same order ``merge_lora`` bakes in (merged weights
    project, then rotate).
    """
    dt = cfg.compute_dtype
    q = jnp.einsum("btd,dhn->bthn", h, matmul_weight(lp["wq"], dt))
    kv = jnp.einsum("btd,dchn->btchn", h, matmul_weight(lp["wkv"], dt))
    if lora is not None and "wq" in lora:
        a, b = lora["wq"]
        q = q + _bgmv_delta(h, a, b, lora_scale, dt).reshape(q.shape)
    if lora is not None and "wkv" in lora:
        a, b = lora["wkv"]
        kv = kv + _bgmv_delta(h, a, b, lora_scale, dt).reshape(kv.shape)
    k, v = kv[:, :, 0], kv[:, :, 1]
    # Saved under remat_policy="dots". RoPE is linear in its input at
    # fixed positions, so its VJP needs only cos/sin (recomputed from
    # positions) — saving POST-rope values loses nothing.
    return (
        checkpoint_name(_rope(q, positions, cfg.rope_theta), "qkv_out"),
        checkpoint_name(_rope(k, positions, cfg.rope_theta), "qkv_out"),
        checkpoint_name(v, "qkv_out"),
    )


def _mlp_block(x, lp, cfg: TransformerConfig, lora=None,
               lora_scale: float = 1.0):
    """Residual SwiGLU MLP (ln2 -> gate/up -> silu -> down). Shared with
    ``generate.py`` (same single-source rationale as ``_project_qkv``).
    ``lora``: per-slot (a, b) views for this layer, as in _project_qkv."""
    dt = cfg.compute_dtype
    h = _rms_norm(x, lp["ln2"])
    gate_up = checkpoint_name(
        jnp.einsum("btd,dcf->btcf", h, matmul_weight(lp["wi"], dt)),
        "mlp_gate_up",
    )
    if lora is not None and "wi" in lora:
        a, b = lora["wi"]
        gate_up = gate_up + _bgmv_delta(h, a, b, lora_scale, dt).reshape(
            gate_up.shape
        )
    ff = jax.nn.silu(gate_up[:, :, 0]) * gate_up[:, :, 1]
    down = jnp.einsum("btf,fd->btd", ff, matmul_weight(lp["wdown"], dt))
    if lora is not None and "wdown" in lora:
        a, b = lora["wdown"]
        down = down + _bgmv_delta(ff, a, b, lora_scale, dt)
    return x + down


def _layer(x, lp, cfg: TransformerConfig, positions, mesh: Mesh | None):
    """One decoder block. x: [B, T, d] global arrays (auto-SPMD)."""
    dt = cfg.compute_dtype
    h = _rms_norm(x, lp["ln1"])
    q, k, v = _project_qkv(h, lp, cfg, positions)
    if cfg.seq_parallel:
        if mesh is None:
            raise ValueError("seq_parallel=True requires a mesh")
        tp = mesh.shape.get("tp", 1)
        if cfg.kv_heads % tp:
            # Both schemes shard KV heads over tp; when Hkv doesn't
            # divide tp, fall back to full heads (correct, g-times the
            # collective bytes).
            groups = cfg.n_heads // cfg.kv_heads
            k = jnp.repeat(k, groups, axis=2)
            v = jnp.repeat(v, groups, axis=2)
        # Only attention needs manual collectives; everything around it
        # stays auto-sharded SPMD. Ring circulates the grouped K/V (1/g
        # the ICI bytes per hop); Ulysses swaps to a full-sequence layout
        # so the flash kernel runs per shard (parallel/ulysses.py).
        if cfg.context_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown context_parallel={cfg.context_parallel!r}: "
                "expected ring|ulysses"
            )
        if cfg.context_parallel == "ulysses":
            attn = ulysses_attention(
                q, k, v, mesh, axis_name="sp", causal=True,
                batch_axes=("dp", "fsdp"), head_axes="tp",
                attn_fn=ulysses_inner_attn(cfg.attention),
            )
        else:
            attn = ring_attention(
                q, k, v, mesh, axis_name="sp", causal=True,
                batch_axes=("dp", "fsdp"), head_axes="tp",
                # cfg.attention's force semantics extend to the hops:
                # "plain" must really rule out the Mosaic kernel.
                hop_attention=cfg.attention,
            )
    else:
        attn = flash_or_plain(
            q, k, v, attention=cfg.attention, causal=True, mesh=mesh
        )
    # Named so the "dots" remat policy can save it: the flash kernel is a
    # custom call, not a dot_general, so a dots-based policy would re-run
    # it during the backward recompute.
    attn = checkpoint_name(attn, "attn_out")
    # wo_out saved too: the MLP VJP needs the post-residual activation,
    # which is then an elementwise add of saved values instead of a
    # re-run of this projection.
    x = x + checkpoint_name(
        jnp.einsum("bthn,hnd->btd", attn, matmul_weight(lp["wo"], dt)), "wo_out"
    )
    return _mlp_block(x, lp, cfg)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """tokens: [B, S] int32 (global) -> logits [B, S, vocab] (f32)."""
    dt = cfg.compute_dtype
    B, S = tokens.shape
    positions = jnp.arange(S)
    x = embed_lookup(params["embed"], tokens, dt)
    layer_fn = functools.partial(_layer, cfg=cfg, positions=positions, mesh=mesh)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.save_only_these_names(
                "qkv_out", "attn_out", "wo_out", "mlp_gate_up"
            )
            layer_fn = jax.checkpoint(layer_fn, policy=policy)
        elif cfg.remat_policy == "full":
            layer_fn = jax.checkpoint(layer_fn)
        else:
            raise ValueError(
                f"unknown remat_policy={cfg.remat_policy!r}: expected full|dots"
            )
    x = jax.lax.scan(lambda c, lp: (layer_fn(c, lp), None), x, params["layers"])[0]
    x = _rms_norm(x, params["final_norm"])
    return jnp.einsum(
        "btd,dv->btv", x, matmul_weight(params["out"], dt)
    ).astype(jnp.float32)


def loss_fn(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Next-token cross-entropy, mean over [B, S-1]."""
    logits = forward(params, tokens, cfg, mesh)
    targets = tokens[:, 1:]
    lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# --- training ---------------------------------------------------------------

def make_optimizer(lr: float = 3e-4, **kw) -> optax.GradientTransformation:
    """AdamW + clip (+ warmup-cosine with total_steps=...); see optim.py."""
    from .optim import make_optimizer as _mk

    return _mk(lr, **kw)


def make_train_step(
    mesh: Mesh, cfg: TransformerConfig, optimizer=None, accum_steps: int = 1
):
    """Jitted sharded train step: (params, opt_state, tokens) -> (params, opt_state, loss).

    Data shards [('dp','fsdp'), 'sp'] — batch over data axes, sequence over
    the ring axis. Params/opt-state keep their NamedShardings (donated).

    ``accum_steps > 1`` splits the batch into that many microbatches and
    accumulates gradients over a ``lax.scan`` before the single optimizer
    update — activation memory drops to one microbatch's worth while the
    update equals the full-batch step up to f32 summation-order rounding
    (mean-of-means over equal microbatches; pinned by tests). The fractional-HBM knob on the
    training side: a pod on a small ``tpu-mem`` slice raises
    ``accum_steps`` instead of shrinking its effective batch.
    """
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    data_sh = batch_sharding(mesh, seq_parallel=cfg.seq_parallel)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def grads_of(params, tokens):
        return jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)

    def step(params, opt_state, tokens):
        if accum_steps == 1:
            loss, grads = grads_of(params, tokens)
        else:
            B = tokens.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"batch {B} not divisible by accum_steps={accum_steps}"
                )
            # Strided split: microbatch i takes every accum_steps-th row,
            # so each microbatch stays evenly spread over the ('dp',
            # 'fsdp') batch shards — a contiguous split would put a whole
            # microbatch on a fraction of the devices and force a
            # reshard (or idle devices) every accumulation step.
            micros = tokens.reshape(B // accum_steps, accum_steps, -1).swapaxes(0, 1)

            def accum(carry, micro):
                loss_sum, grads = carry
                l, g = grads_of(params, micro)
                return (loss_sum + l, jax.tree.map(jnp.add, grads, g)), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            (loss_sum, grads), _ = jax.lax.scan(
                accum, (jnp.float32(0.0), zeros), micros
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(psh, None, data_sh),
        out_shardings=(psh, None, None),
        donate_argnums=(0, 1),
    )


def init_train_state(rng: jax.Array, mesh: Mesh, cfg: TransformerConfig, optimizer=None):
    """Sharded (params, opt_state) ready for :func:`make_train_step`.

    Init runs under jit with ``out_shardings`` so every weight is created
    directly in its shard — no host-side or single-device materialization
    (an 8B-param f32 init would otherwise OOM one chip before training even
    starts, and ``device_put`` cannot target non-addressable devices on
    multi-host meshes).
    """
    opt = optimizer or make_optimizer()
    psh = param_shardings(mesh, cfg)
    params = jax.jit(lambda k: init_params(k, cfg), out_shardings=psh)(rng)
    # Moment buffers inherit each param's sharding via zeros_like; scalar
    # counters get committed mesh-replicated (uncommitted scalars collide
    # with mesh-sharded params after a checkpoint restore).
    opt_state = commit_to_mesh(opt.init(params), mesh)
    return params, opt_state


def demo_batch(rng: jax.Array, batch: int, seq: int, vocab: int) -> jax.Array:
    """Synthetic structured tokens (zero-egress image: no dataset downloads)."""
    base = jax.random.randint(rng, (batch, 1), 0, vocab // 2)
    ramp = jnp.arange(seq)[None, :]
    return ((base + ramp) % vocab).astype(jnp.int32)
