"""Demo JAX workloads — the pods the plugin schedules.

These are the BASELINE.md config workloads: an MNIST-scale MLP (config 2),
a ResNet-50 classifier and a BERT-style MLM encoder (config 3's two
binpacked pods), and a Llama-style decoder transformer (configs 3-4, the
flagship) whose training step shards over a dp/fsdp/tp(+sp)
`jax.sharding.Mesh` built from the topology the plugin injected
(``parallel/podenv.py``). The reference repo ships only YAML demo pods
(``demo/binpack-1/``); here the demo workloads are first-class, testable
code.
"""

from .bert import BertConfig  # noqa: F401
# NOTE: only make_generate/make_speculative_generate/sample_logits are
# re-exported by name — re-exporting the `generate` function would shadow
# the `workloads.generate` submodule.
from .generate import (  # noqa: F401
    make_generate,
    make_speculative_generate,
    sample_logits,
)
from .convert import from_hf_llama, to_hf_llama  # noqa: F401
from .lora import LoraConfig, init_lora, make_lora_train_step, merge_lora  # noqa: F401
from .optim import make_optimizer  # noqa: F401
from .resnet import ResNetConfig  # noqa: F401
from .trainer import TrainLoopConfig, run_train_loop  # noqa: F401
from .transformer import TransformerConfig, llama3_8b  # noqa: F401
