"""Training-loop runner: steps, logging, orbax checkpoint/resume.

The plugin half of the framework keeps ITS durable state in the Kubernetes
API ("apiserver is the database", SURVEY.md section 5 — the reference has
no checkpointing of its own); this module is the workload half: a pod that
gets preempted, rescheduled, or resized by the binpack scheduler resumes
training from its last checkpoint instead of restarting.

Design (TPU-first):
- **uniform Task protocol** over the demo workloads (decoder, BERT,
  ResNet): opaque state pytree in, (state, loss) out — the loop never
  inspects model internals, so anything jit-shardable plugs in.
- **orbax CheckpointManager** — async saves (training continues while the
  checkpoint writes), multi-host coordination handled by orbax itself on
  ``jax.distributed``-initialized slices, restore lands each shard
  directly on its device via sharded abstract targets (no host gather).
- **deterministic data** — batches derive from ``fold_in(rng, step)``, so
  an interrupted+resumed run reproduces the uninterrupted trajectory
  exactly (tested to bitwise equality on CPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Protocol

import jax

from ..utils.log import get_logger

log = get_logger("workloads.trainer")


@dataclasses.dataclass(frozen=True)
class TrainLoopConfig:
    total_steps: int = 100
    log_every: int = 10
    ckpt_dir: str = ""  # empty: checkpointing off
    ckpt_every: int = 50
    ckpt_keep: int = 3


class Task(Protocol):
    """Adapter between a workload module and the generic loop."""

    def init_state(self, rng: jax.Array, mesh) -> Any:
        """Sharded training state pytree (params, opt state, ...)."""
        ...

    def make_step(self, mesh) -> Callable[[Any, Any], tuple[Any, jax.Array]]:
        """Jitted (state, batch) -> (state, loss)."""
        ...

    def make_batch(self, rng: jax.Array, step: int) -> Any:
        """Batch pytree for this step (deterministic in (rng, step))."""
        ...


def _abstract_like(state: Any) -> Any:
    """Shape/dtype/sharding skeleton for a sharded orbax restore."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


def run_train_loop(
    task: Task,
    mesh,
    cfg: TrainLoopConfig,
    rng: jax.Array,
    *,
    on_metrics: Callable[[int, float], None] | None = None,
) -> tuple[Any, float]:
    """Run (or resume) training; returns (final_state, last_loss)."""
    k_init, k_data = jax.random.split(rng)
    state = task.init_state(k_init, mesh)
    step_fn = task.make_step(mesh)
    start = 0

    mgr = None
    if cfg.ckpt_dir:
        import orbax.checkpoint as ocp

        mgr = ocp.CheckpointManager(
            cfg.ckpt_dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.ckpt_keep, enable_async_checkpointing=True
            ),
        )
        latest = mgr.latest_step()
        if latest is not None:
            state = mgr.restore(
                latest, args=ocp.args.StandardRestore(_abstract_like(state))
            )
            start = latest + 1
            log.info("resumed from checkpoint step %d", latest)

    loss = float("nan")
    for step in range(start, cfg.total_steps):
        batch = task.make_batch(jax.random.fold_in(k_data, step), step)
        state, loss_arr = step_fn(state, batch)
        if cfg.log_every and (step % cfg.log_every == 0 or step == cfg.total_steps - 1):
            loss = float(jax.block_until_ready(loss_arr))
            log.info("step %d loss %.4f", step, loss)
            if on_metrics is not None:
                on_metrics(step, loss)
        if mgr is not None and cfg.ckpt_every and (step + 1) % cfg.ckpt_every == 0:
            mgr.save(step, args=ocp.args.StandardSave(state))
    if mgr is not None:
        # Persist the final step too (idempotent if it matched ckpt_every).
        if cfg.total_steps > start and mgr.latest_step() != cfg.total_steps - 1:
            mgr.save(cfg.total_steps - 1, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
        mgr.close()
    if loss != loss and cfg.total_steps > start:  # never logged: compute now
        loss = float(jax.block_until_ready(loss_arr))
    return state, loss


# --- task adapters for the demo workloads ----------------------------------


class DecoderTask:
    """Llama-style decoder LM (``workloads/transformer.py``)."""

    def __init__(self, cfg, batch: int, seq: int):
        self.cfg, self.batch, self.seq = cfg, batch, seq

    def init_state(self, rng, mesh):
        from . import transformer as T

        return tuple(T.init_train_state(rng, mesh, self.cfg))

    def make_step(self, mesh):
        from . import transformer as T

        step = T.make_train_step(mesh, self.cfg)

        def fn(state, batch):
            params, opt_state, loss = step(state[0], state[1], batch)
            return (params, opt_state), loss

        return fn

    def make_batch(self, rng, step):
        from . import transformer as T

        return T.demo_batch(rng, self.batch, self.seq, self.cfg.vocab)


class BertTask:
    """BERT MLM encoder (``workloads/bert.py``)."""

    def __init__(self, cfg, batch: int, seq: int):
        self.cfg, self.batch, self.seq = cfg, batch, seq

    def init_state(self, rng, mesh):
        from . import bert as B

        return tuple(B.init_train_state(rng, mesh, self.cfg))

    def make_step(self, mesh):
        from . import bert as B

        step = B.make_train_step(mesh, self.cfg)

        def fn(state, batch):
            tokens, targets, mask = batch
            params, opt_state, loss = step(state[0], state[1], tokens, targets, mask)
            return (params, opt_state), loss

        return fn

    def make_batch(self, rng, step):
        from . import bert as B

        return B.demo_batch(rng, self.batch, self.seq, self.cfg)


class ResNetTask:
    """ResNet classifier (``workloads/resnet.py``)."""

    def __init__(self, cfg, batch: int, image_size: int = 32):
        self.cfg, self.batch, self.image_size = cfg, batch, image_size

    def init_state(self, rng, mesh):
        from . import resnet as R

        return tuple(R.init_train_state(rng, mesh, self.cfg))

    def make_step(self, mesh):
        from . import resnet as R

        step = R.make_train_step(mesh, self.cfg)

        def fn(state, batch):
            images, labels = batch
            params, bn, opt_state, loss = step(
                state[0], state[1], state[2], images, labels
            )
            return (params, bn, opt_state), loss

        return fn

    def make_batch(self, rng, step):
        from . import resnet as R

        return R.demo_batch(rng, self.batch, self.image_size)
