"""Resource names, annotation/env keys and wire constants.

TPU-native analog of the reference's ``pkg/gpu/nvidia/const.go:8-38``: the
resource-name pair, the unix-socket name, the pod selector label, and the
annotation/env key family used to persist allocation decisions in the
Kubernetes API ("apiserver is the database").
"""

from __future__ import annotations

import enum

# --- Extended resource names (reference: const.go:11-12) -------------------
# Fractional HBM, counted in memory units (1 fake device per unit).
RESOURCE_MEM = "aliyun.com/tpu-mem"
# Whole-chip resource for pods that want exclusive chips.
RESOURCE_CORE = "aliyun.com/tpu-core"
# Physical chip count, patched into node status (reference: gpu-count).
RESOURCE_COUNT = "aliyun.com/tpu-count"

# GPU names kept for the mixed-fleet scheduler-extender path (BASELINE cfg 5).
RESOURCE_GPU_MEM = "aliyun.com/gpu-mem"
RESOURCE_GPU_COUNT = "aliyun.com/gpu-count"
# The GPU family's annotation/env keys (the reference repo's originals),
# used by the extender's mixed-fleet vocabulary (extender/logic.py
# RESOURCE_FAMILIES). Declared here like the TPU family below — tpulint's
# string-consts rule forbids inline ALIYUN_COM_* literals anywhere else.
ENV_GPU_MEM_IDX = "ALIYUN_COM_GPU_MEM_IDX"
ENV_GPU_MEM_POD = "ALIYUN_COM_GPU_MEM_POD"
ENV_GPU_MEM_DEV = "ALIYUN_COM_GPU_MEM_DEV"
ENV_GPU_MEM_ASSIGNED = "ALIYUN_COM_GPU_MEM_ASSIGNED"
ENV_GPU_MEM_ASSUME_TIME = "ALIYUN_COM_GPU_MEM_ASSUME_TIME"

# --- Device-plugin sockets (reference: const.go:13) ------------------------
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"
KUBELET_SOCKET = DEVICE_PLUGIN_PATH + "kubelet.sock"
MEM_SOCKET_NAME = "aliyuntpushare.sock"
CORE_SOCKET_NAME = "aliyuntpucore.sock"
API_VERSION = "v1beta1"

# --- Pod selector label (reference: const.go:17-18) ------------------------
LABEL_RESOURCE_KEY = "tpu/resource"
LABEL_RESOURCE_VALUE = "tpu-mem"
# Whole-chip pods get the same key with this value so both allocation kinds
# are discoverable by one label key (the reference had a single resource).
LABEL_CORE_VALUE = "tpu-core"

# --- Annotation / env key family (reference: const.go:27-34) ---------------
ENV_MEM_IDX = "ALIYUN_COM_TPU_MEM_IDX"  # assigned physical chip index
ENV_MEM_POD = "ALIYUN_COM_TPU_MEM_POD"  # this pod's tpu-mem request
ENV_MEM_CONTAINER = "ALIYUN_COM_TPU_MEM_CONTAINER"  # container's request
ENV_MEM_DEV = "ALIYUN_COM_TPU_MEM_DEV"  # total units on assigned chip
ENV_ASSIGNED_FLAG = "ALIYUN_COM_TPU_MEM_ASSIGNED"  # "false" until kubelet admits
ENV_ASSUME_TIME = "ALIYUN_COM_TPU_MEM_ASSUME_TIME"  # ns timestamp of assignment
# Whole-chip (tpu-core) holds: comma-separated chip indices granted to the
# pod. Persisted so restart re-derives exclusive holds from the apiserver
# and the mem binpack can exclude core-held chips (accounting model:
# server.go:268-289 extended across both resources).
ENV_CORE_IDS = "ALIYUN_COM_TPU_CORE_IDS"
ENV_CORE_POD = "ALIYUN_COM_TPU_CORE_POD"  # this pod's tpu-core request

# --- Gang (multi-chip) scheduling ------------------------------------------
# A pod opts into a topology-aware multi-chip gang by annotating its spec
# with the slice shape it needs — "2x2x1" (exact v4/v5-style grid) or a
# bare chip count "4" (any arrangement). Its aliyun.com/tpu-mem limit is
# the TOTAL across the gang; per-chip share = total / shape size.
ANN_GANG_SHAPE = "tpushare.aliyun.com/gang-shape"
# A pod may additionally name a gang GROUP: pods sharing the group id
# are one distributed job whose members land on (possibly) different
# nodes and must be admitted all-or-nothing. Group admission runs the
# sharded extender's cross-shard two-phase reserve (extender/shards.py):
# every member shard books its chips as a journaled "gang2pc"
# reservation before any member binds, and a leader decision commits or
# aborts the whole group.
ANN_GANG_GROUP = "tpushare.aliyun.com/gang-group"
# Disaggregated-serving tier of a group member (serving/handoff.py): a
# two-tier slice is admitted as ONE gang group — a prefill gang plus a
# decode gang, all-or-nothing through the same cross-shard two-phase
# reserve — with each member pod declaring which tier it serves. The
# SLO router scales the tiers independently (TTFT pressure -> prefill
# capacity, TPOT pressure -> decode capacity); the inspect CLI renders
# the composition as a TIER column and in `inspect why`. Absent = a
# unified (non-disaggregated) serving pod; unknown values are ignored.
ANN_SERVING_TIER = "tpushare.aliyun.com/serving-tier"
SERVING_TIER_PREFILL = "prefill"
SERVING_TIER_DECODE = "decode"
SERVING_TIERS = (SERVING_TIER_PREFILL, SERVING_TIER_DECODE)
# Persisted gang decision (annotations on the pod, mirrored into env):
# comma-separated member chip indices, the normalized shape, and the HBM
# units claimed on EACH member chip. A gang is only ever persisted whole
# — all member chips in one PATCH — or not at all (the all-or-nothing
# claim protocol, docs/scheduling.md).
ENV_GANG_CHIPS = "ALIYUN_COM_TPU_GANG_CHIPS"
ENV_GANG_SHAPE = "ALIYUN_COM_TPU_GANG_SHAPE"
ENV_GANG_PER_CHIP = "ALIYUN_COM_TPU_GANG_PER_CHIP"
# Node label declaring the host's chip grid ("2x2x1"); absent or garbled,
# the scheduler derives the default grid from the advertised chip count
# (topology.ChipTopology.default_for).
LABEL_NODE_TOPOLOGY = "tpushare.aliyun.com/topology"

# --- TPU workload env (analog of NVIDIA_VISIBLE_DEVICES, const.go:27) ------
ENV_TPU_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_TPU_PROCESS_BOUNDS = "TPU_PROCESS_BOUNDS"
ENV_TPU_CHIPS_PER_PROCESS_BOUNDS = "TPU_CHIPS_PER_PROCESS_BOUNDS"
# TPU-VM metadata envs the tpuvm discovery backend probes (set by the GCE
# runtime on real TPU hosts; discovery/tpuvm.py also accepts the
# unprefixed legacy spellings, which carry no TPU_ prefix and live there).
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"

# --- Multi-host slice bootstrap (BASELINE cfg 4; no reference analog — the
# reference has no comms backend, SURVEY.md section 2). One pod per host;
# these envs parameterize jax.distributed.initialize so the per-host JAX
# processes form one global mesh over ICI/DCN.
ENV_COORDINATOR_ADDRESS = "TPUSHARE_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "TPUSHARE_NUM_PROCESSES"
ENV_PROCESS_ID = "TPUSHARE_PROCESS_ID"
# Cooperative HBM cap for the JAX/XLA client in the pod (the TPU analog of the
# reference's cGPU isolation toggle, podmanager.go:59-72: there is no hardware
# fence, the runtime must self-limit).
ENV_XLA_MEM_FRACTION = "TPU_HBM_LIMIT_FRACTION"
ENV_XLA_PYTHON_MEM_FRACTION = "XLA_PYTHON_CLIENT_MEM_FRACTION"
ENV_XLA_PYTHON_PREALLOCATE = "XLA_PYTHON_CLIENT_PREALLOCATE"

# Node label that disables the cooperative HBM cap (reference: const.go:35,
# label cgpu.disable.isolation=true read at podmanager.go:59-72).
LABEL_DISABLE_ISOLATION = "ctpu.disable.isolation"

# --- Tracing (utils/tracing.py) --------------------------------------------
# "trace_id:span_id" of the admission trace, written by the scheduler
# extender with its bind annotations and adopted by the device plugin's
# allocator after the pod match — the cross-process stitch that makes
# filter -> bind -> WAL -> PATCH -> Allocate -> env one trace. Must stay
# equal to utils.tracing.TRACE_ANNOTATION (that module is import-light
# by design; test_tracing pins the two strings agree).
ANN_TRACE_ID = "tpushare.aliyun.com/trace-id"

# --- Workload classes (interference observability, cluster/interference.py)
# A pod declares its QoS class on its spec; admission normalizes and
# re-persists it with the decision PATCH (and mirrors it into the
# container env) so every downstream consumer — informer indexes, the
# interference detector, the inspect CLI, the serving engine's governor —
# reads one canonical value. Unknown/absent values normalize to
# latency-critical: the safe default is to protect, never to throttle.
ANN_WORKLOAD_CLASS = "tpushare.aliyun.com/workload-class"
WORKLOAD_LATENCY_CRITICAL = "latency-critical"
WORKLOAD_BEST_EFFORT = "best-effort"
WORKLOAD_CLASSES = (WORKLOAD_LATENCY_CRITICAL, WORKLOAD_BEST_EFFORT)
ENV_WORKLOAD_CLASS = "ALIYUN_COM_TPU_WORKLOAD_CLASS"

# Per-tenant LoRA adapter id (serving/adapters.py): the pod declares
# which fine-tune its requests decode through; admission re-persists the
# id with the decision PATCH (the workload-class precedent) and
# Allocate mirrors it into the container env so the serving engine can
# default its requests' adapter — and prefetch the adapter's paged slab
# load — straight from PodTpuEnv. Free-form id, empty = base model; the
# engine validates it against its lora_store at request admission.
ANN_LORA_ADAPTER = "tpushare.aliyun.com/lora-adapter"
ENV_LORA_ADAPTER = "ALIYUN_COM_TPU_LORA_ADAPTER"

# The serving engine's SLO tier names (serving/engine.py aliases these —
# they live here so jax-free control-plane code, e.g. the daemon's
# per-tier trace-sampling flags, can name a tier without importing the
# engine). The workload-class -> tier mapping is 1:1:
# latency-critical -> critical, best-effort -> best_effort.
SLO_TIER_CRITICAL = "critical"
SLO_TIER_BEST_EFFORT = "best_effort"

# Fleet replica lifecycle states (serving/router.py's membership table;
# they live here so the jax-free CLI can render a replica map without
# importing the router). ready -> routable; cordoned -> serving its
# in-flight work but closed to new routes (the scale-down protocol's
# first durable step); draining -> snapshot capture in progress;
# dead -> failure detector evicted it (consecutive scrape misses) or
# scale-down released it.
FLEET_REPLICA_READY = "ready"
FLEET_REPLICA_CORDONED = "cordoned"
FLEET_REPLICA_DRAINING = "draining"
FLEET_REPLICA_DEAD = "dead"
FLEET_REPLICA_STATES = (
    FLEET_REPLICA_READY,
    FLEET_REPLICA_CORDONED,
    FLEET_REPLICA_DRAINING,
    FLEET_REPLICA_DEAD,
)

# Node annotation carrying the interference detector's latest verdicts as
# JSON ({"chips": {chip: {"victim", "aggressors", "ratio"}}, "time_unix"})
# — written best-effort each detector pass so kubectl-inspect-tpushare
# (and its `top` view) can render co-tenant interference with no extra
# endpoint ("apiserver is the database", as ever).
ANN_INTERFERENCE = "tpushare.aliyun.com/interference"

# --- Live defragmentation (allocator/defrag.py) ----------------------------
# Node annotation carrying the daemon's defragmenter status as JSON:
# {"planned", "active", "completed", "failed", "last_move_ms", "quantum",
#  "stranded_units", "stranded_pct"} — written best-effort after every
# defrag pass so kubectl-inspect-tpushare can render per-node MOVES and
# stranded-HBM columns with no extra endpoint ("apiserver is the
# database", as ever).
ANN_DEFRAG_STATUS = "tpushare.aliyun.com/defrag-status"

# --- Scheduler-extender annotation (reference: cmd/inspect/main.go:23) -----
# JSON map[containerName]map[chipIdx]memUnits written by the extender at bind
# time; the inspect CLI prefers it for per-chip attribution.
ANN_EXTENDER_ALLOCATION = "scheduler.framework.tpushare.allocation"

# --- Crash-safe state (allocator/checkpoint.py) ----------------------------
# Node annotation carrying the fencing state, formatted
# "<generation>:<incarnation token>": the newest daemon instance stamps
# its checkpoint generation + a random per-open token here at (re)build.
# An instance observing a higher generation — or its own generation under
# a foreign token (two instances raced the acquire to the same number;
# the last PATCH writer owns it) — is stale and refuses allocation writes.
ANN_FENCE_GENERATION = "tpushare.aliyun.com/fence-generation"

# Optimistic-lock conflict marker in apiserver patch errors
# (reference: const.go:15).
OPTIMISTIC_LOCK_ERROR_MSG = "the object has been modified; please apply your changes to the latest version and try again"


class MemoryUnit(str, enum.Enum):
    """Granularity of one fake device (reference: const.go:8,37-38)."""

    GiB = "GiB"
    MiB = "MiB"

    @property
    def num_bytes(self) -> int:
        return 1 << 30 if self is MemoryUnit.GiB else 1 << 20


def translate_memory_units(value: str | None) -> MemoryUnit:
    """Validate a ``--memory-unit`` flag value, defaulting to GiB.

    Reference: ``cmd/nvidia/main.go:67-78``.
    """
    if value is None or value == "":
        return MemoryUnit.GiB
    try:
        return MemoryUnit(value)
    except ValueError:
        raise ValueError(
            f"invalid memory unit {value!r}: expected one of "
            f"{[u.value for u in MemoryUnit]}"
        ) from None
