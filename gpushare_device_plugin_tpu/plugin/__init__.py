from .server import PluginConfig, TpuSharePlugin

__all__ = ["PluginConfig", "TpuSharePlugin"]
