from . import deviceplugin_pb2 as pb
from .api_grpc import (
    DevicePluginServicer,
    DevicePluginStub,
    RegistrationServicer,
    RegistrationStub,
    add_device_plugin_servicer,
    add_registration_servicer,
)

__all__ = [
    "pb",
    "DevicePluginServicer",
    "DevicePluginStub",
    "RegistrationServicer",
    "RegistrationStub",
    "add_device_plugin_servicer",
    "add_registration_servicer",
]
