"""Hand-written gRPC bindings for the v1beta1 device-plugin API.

grpc_tools (the protoc gRPC python plugin) is not available in the build
image, so the service stubs/servicers normally emitted into
``*_pb2_grpc.py`` are written by hand against the generated message classes.
Method paths must match the canonical API exactly
(``/v1beta1.Registration/Register`` etc.) — kubelet dials these by name.
"""

from __future__ import annotations

import grpc

from . import deviceplugin_pb2 as pb

_REGISTRATION = "v1beta1.Registration"
_DEVICE_PLUGIN = "v1beta1.DevicePlugin"


# --- client stubs ----------------------------------------------------------


class RegistrationStub:
    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{_REGISTRATION}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )


class DevicePluginStub:
    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{_DEVICE_PLUGIN}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


# --- servicer base classes -------------------------------------------------


class RegistrationServicer:
    def Register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


class DevicePluginServicer:
    def GetDevicePluginOptions(self, request, context) -> pb.DevicePluginOptions:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def ListAndWatch(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def GetPreferredAllocation(self, request, context) -> pb.PreferredAllocationResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def Allocate(self, request, context) -> pb.AllocateResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()

    def PreStartContainer(self, request, context) -> pb.PreStartContainerResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError()


# --- server registration ---------------------------------------------------


def add_registration_servicer(servicer: RegistrationServicer, server: grpc.Server) -> None:
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_REGISTRATION, handlers),)
    )


def add_device_plugin_servicer(servicer: DevicePluginServicer, server: grpc.Server) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_DEVICE_PLUGIN, handlers),)
    )
