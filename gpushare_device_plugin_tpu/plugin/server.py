"""Device-plugin gRPC server + kubelet registration.

TPU-native port of the reference's L4 (``server.go:89-245``): serve the four
``v1beta1.DevicePlugin`` RPCs on a unix socket under the kubelet
device-plugin dir, self-dial to confirm liveness, then register the
resource name with kubelet, which calls back with ListAndWatch/Allocate.

Improvements over the reference, deliberate:
- ListAndWatch supports health *recovery* (reference marks unhealthy as
  terminal, FIXME ``server.go:184``) and coalesces a burst of per-fake-device
  events into one re-send (the reference re-streams the full list once per
  fake device of a failed chip, ``server.go:183-186``).
- Multiple concurrent ListAndWatch streams are supported (kubelet restarts
  mid-stream leave stale streams behind until their sends fail).
"""

from __future__ import annotations

import dataclasses
import os
import time
from concurrent import futures
from typing import Callable, Sequence

import grpc

from .. import const
from ..device.fanout import DeviceInventory, FakeDevice
from ..discovery.base import ChipHealth
from ..utils.log import get_logger
from ..utils.lockrank import make_condition
from ..utils.metric_catalog import ALLOCATE_SECONDS, ALLOCATE_TOTAL
from ..utils.tracing import TRACER
from .api import (
    DevicePluginServicer,
    DevicePluginStub,
    RegistrationStub,
    add_device_plugin_servicer,
    pb,
)

log = get_logger("plugin.server")

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


@dataclasses.dataclass
class PluginConfig:
    resource_name: str = const.RESOURCE_MEM
    socket_name: str = const.MEM_SOCKET_NAME
    plugin_dir: str = const.DEVICE_PLUGIN_PATH
    kubelet_socket: str = ""  # default: <plugin_dir>/kubelet.sock
    api_version: str = const.API_VERSION
    grpc_workers: int = 8
    pre_start_required: bool = False

    @property
    def socket_path(self) -> str:
        return os.path.join(self.plugin_dir, self.socket_name)

    @property
    def kubelet_socket_path(self) -> str:
        return self.kubelet_socket or os.path.join(self.plugin_dir, "kubelet.sock")


class TpuSharePlugin(DevicePluginServicer):
    """One plugin instance per resource name (tpu-mem, tpu-core)."""

    def __init__(
        self,
        inventory: DeviceInventory,
        allocate_fn: Callable[[Sequence[Sequence[str]]], list] | None,
        config: PluginConfig | None = None,
        devices_fn: Callable[..., list[FakeDevice]] | None = None,
        preferred_fn: Callable[[list[str], int], list[str]] | None = None,
    ):
        """``allocate_fn`` receives the per-container granted fake-ID lists
        and returns ``ContainerAllocation``s (see allocator.env); raising
        ``Exception`` maps to a gRPC error, which kubelet surfaces as an
        UnexpectedAdmissionError for the pod (``allocate.go:99-105``).

        ``devices_fn(health=...)`` overrides the advertised device list
        (default: the fractional-HBM fan-out). ``preferred_fn(available,
        size)`` orders GetPreferredAllocation picks (the core plugin steers
        kubelet away from chips with fractional usage).
        """
        self._inv = inventory
        self._allocate_fn = allocate_fn
        self._cfg = config or PluginConfig()
        self._devices_fn = devices_fn or inventory.mem_fake_devices
        self._preferred_fn = preferred_fn
        self._health: dict[str, ChipHealth] = {}
        self._cond = make_condition("plugin.stream")
        self._version = 0  # bumped on every health change
        self._stopping = False
        self._inflight_allocates = 0  # guarded by _cond; drain() waits on it
        self._server: grpc.Server | None = None

    @property
    def resource_name(self) -> str:
        return self._cfg.resource_name

    @property
    def socket_path(self) -> str:
        return self._cfg.socket_path

    # ------------------------------------------------------------------
    # health ingestion (fed by the manager's health watcher thread)
    # ------------------------------------------------------------------

    def set_allocate_fn(self, fn: Callable[[Sequence[Sequence[str]]], list]) -> None:
        """Late-bind the allocator (it may need this plugin's health view)."""
        self._allocate_fn = fn

    def set_chip_health(self, chip_id: str | None, health: ChipHealth) -> None:
        """Mark one chip (or all, when ``chip_id`` is None) and wake streams."""
        with self._cond:
            if chip_id is None:
                for chip in self._inv.chips():
                    self._health[chip.id] = health
            else:
                self._health[chip_id] = health
            self._version += 1
            self._cond.notify_all()

    def unhealthy_chip_indices(self) -> list[int]:
        with self._cond:
            known = {c.id for c in self._inv.chips()}
            return sorted(
                self._inv.index_of(cid)
                for cid, h in self._health.items()
                if h == ChipHealth.UNHEALTHY and cid in known
            )

    # ------------------------------------------------------------------
    # DevicePlugin RPCs
    # ------------------------------------------------------------------

    def GetDevicePluginOptions(self, request, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions(
            pre_start_required=self._cfg.pre_start_required,
            get_preferred_allocation_available=True,
        )

    def _snapshot(self) -> pb.ListAndWatchResponse:
        devices = self._devices_fn(health=dict(self._health))
        return pb.ListAndWatchResponse(
            devices=[
                pb.Device(ID=d.id, health=HEALTHY if d.healthy else UNHEALTHY)
                for d in devices
            ]
        )

    def ListAndWatch(self, request, context):
        """Stream the fake-device list; re-send on health transitions.

        Coalescing: we wait on a version counter, so N chip events between
        two sends produce one re-send of the full list.
        """
        with self._cond:
            sent_version = self._version
        snapshot = self._snapshot()
        yield snapshot
        log.v(
            1,
            "ListAndWatch: initial send of %d devices for %s",
            len(snapshot.devices),
            self._cfg.resource_name,
        )
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._version != sent_version or self._stopping,
                    timeout=1.0,
                )
                if self._stopping or not context.is_active():
                    return
                if self._version == sent_version:
                    continue
                sent_version = self._version
            yield self._snapshot()

    def GetPreferredAllocation(self, request, context) -> pb.PreferredAllocationResponse:
        # Fake HBM-unit devices are fungible (which IDs kubelet grants is
        # irrelevant by design — Allocate only counts them), so the mem
        # plugin takes the first N. The core plugin injects a preferred_fn
        # that steers kubelet toward conflict-free chips.
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            cresp = resp.container_responses.add()
            available = list(creq.available_deviceIDs)
            if self._preferred_fn is not None:
                try:
                    picks = self._preferred_fn(available, creq.allocation_size)
                except Exception as e:  # noqa: BLE001 — preference only
                    log.warning("preferred_fn failed: %s", e)
                    picks = available[: creq.allocation_size]
            else:
                picks = available[: creq.allocation_size]
            cresp.deviceIDs.extend(picks)
        return resp

    def Allocate(self, request, context) -> pb.AllocateResponse:
        """Count granted fake IDs per container and delegate placement."""
        # In-flight accounting for graceful shutdown: a SIGTERM'd daemon
        # drains admissions that already started (their PATCH may be on
        # the wire — dying mid-write is the checkpoint's job to survive,
        # but not dying at all is better) and refuses new ones.
        with self._cond:
            if self._stopping:
                context.abort(
                    grpc.StatusCode.UNAVAILABLE, "plugin is shutting down"
                )
            self._inflight_allocates += 1
        try:
            return self._allocate_inner(request, context)
        finally:
            with self._cond:
                self._inflight_allocates -= 1
                self._cond.notify_all()

    def _allocate_inner(self, request, context) -> pb.AllocateResponse:
        from ..utils.faults import FAULTS
        from ..utils.metrics import REGISTRY

        try:
            FAULTS.fire("plugin.allocate")
        except Exception as e:  # injected kubelet-facing failure
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        granted = [list(creq.devicesIDs) for creq in request.container_requests]
        log.v(4, "Allocate: granted id counts %s", [len(g) for g in granted])
        if self._allocate_fn is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "allocator not bound")
        # The admission's plugin-process root span: the kubelet-facing
        # gRPC entry. The allocator's spans nest under it, and once the
        # pod is matched its trace-id annotation re-parents this whole
        # stack under the extender's bind span (one stitched trace). The
        # latency observation runs inside the span so the histogram
        # bucket carries this admission's trace id as an exemplar.
        with TRACER.span(
            "plugin.allocate",
            attributes={"resource": self._cfg.resource_name},
        ) as sp:
            sp.set_attribute("granted", [len(g) for g in granted])
            t0 = time.perf_counter()
            try:
                allocations = self._allocate_fn(granted)
            except Exception as e:  # business errors -> admission failure
                log.warning("Allocate failed: %s", e)
                REGISTRY.counter_inc(
                    ALLOCATE_TOTAL,
                    "Allocate RPCs by outcome",
                    resource=self._cfg.resource_name, outcome="error",
                )
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            REGISTRY.observe(
                ALLOCATE_SECONDS,
                time.perf_counter() - t0,
                "Allocate placement latency",
                resource=self._cfg.resource_name,
            )
            REGISTRY.counter_inc(
                ALLOCATE_TOTAL,
                "Allocate RPCs by outcome",
                resource=self._cfg.resource_name, outcome="ok",
            )
        resp = pb.AllocateResponse()
        for alloc in allocations:
            cresp = resp.container_responses.add()
            for k, v in alloc.envs.items():
                cresp.envs[k] = v
            for k, v in alloc.annotations.items():
                cresp.annotations[k] = v
            for dev in alloc.devices:
                cresp.devices.add(
                    container_path=dev.container_path,
                    host_path=dev.host_path,
                    permissions=dev.permissions,
                )
        return resp

    def PreStartContainer(self, request, context) -> pb.PreStartContainerResponse:
        # no-op (reference: server.go:195-197)
        return pb.PreStartContainerResponse()

    # ------------------------------------------------------------------
    # lifecycle (reference: server.go:110-245)
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Listen on the plugin socket and confirm liveness by self-dialing."""
        path = self._cfg.socket_path
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(self._cfg.plugin_dir, exist_ok=True)
        self._stopping = False
        self._registered = False
        server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=self._cfg.grpc_workers,
                thread_name_prefix=f"plugin-{self._cfg.resource_name}",
            )
        )
        add_device_plugin_servicer(self, server)
        server.add_insecure_port(f"unix:{path}")
        server.start()
        self._server = server
        # self-dial sanity check (server.go:127-131)
        with grpc.insecure_channel(f"unix:{path}") as ch:
            grpc.channel_ready_future(ch).result(timeout=10)
            DevicePluginStub(ch).GetDevicePluginOptions(pb.Empty(), timeout=5)
        log.v(1, "plugin %s serving on %s", self._cfg.resource_name, path)

    def register(self, timeout: float = 10.0) -> None:
        """Announce this plugin to kubelet (``server.go:154-173``)."""
        with grpc.insecure_channel(f"unix:{self._cfg.kubelet_socket_path}") as ch:
            grpc.channel_ready_future(ch).result(timeout=timeout)
            RegistrationStub(ch).Register(
                pb.RegisterRequest(
                    version=self._cfg.api_version,
                    endpoint=self._cfg.socket_name,
                    resource_name=self._cfg.resource_name,
                    options=pb.DevicePluginOptions(
                        pre_start_required=self._cfg.pre_start_required,
                        get_preferred_allocation_available=True,
                    ),
                ),
                timeout=timeout,
            )
        self._registered = True
        log.v(1, "registered %s with kubelet", self._cfg.resource_name)

    @property
    def registered(self) -> bool:
        """True once this plugin announced itself to kubelet (the
        daemon's ``/readyz`` gate: an unregistered plugin serves no
        pods, whatever its socket says)."""
        return getattr(self, "_registered", False)

    def serve(self) -> None:
        self.start()
        self.register()

    def quiesce(self) -> None:
        """Refuse new Allocate calls from now on, without waiting. The
        manager quiesces every plugin before draining any, so later
        plugins cannot keep admitting work while earlier ones drain."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Refuse new Allocate calls and wait for in-flight ones to finish
        (their apiserver PATCH completes and the journal entry resolves).
        True when the plugin drained inside the timeout; False means the
        caller proceeds to stop anyway — the checkpoint replay covers
        whatever was cut mid-write."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            return self._cond.wait_for(
                lambda: self._inflight_allocates == 0, timeout_s
            )

    def stop(self, grace: float = 1.0) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        path = self._cfg.socket_path
        if os.path.exists(path):
            try:
                os.unlink(path)
            except OSError:
                pass


def wait_for_socket(path: str, timeout: float = 10.0) -> bool:
    """Poll for a unix socket to appear (used by tests and the manager)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.02)
    return os.path.exists(path)
