# Two-stage build, reference analog Dockerfile:1-24 — but with ZERO GPU
# dependency: no nvidia runtime, no driver-time link tricks. The native
# libtpuinfo shim dlopen()s libtpu lazily at runtime (tpuinfo.cpp), so the
# same image runs on TPU-VM nodes and plain CPU nodes (where the daemon
# simply parks, gpumanager.go:36-47 semantics).

# linux/amd64 only: jax[tpu]'s libtpu wheels are manylinux x86_64 (TPU-VM
# hosts are x86_64); build with --platform=linux/amd64 on arm64 machines.
FROM --platform=linux/amd64 python:3.12-slim AS builder
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY . .
# python:3.12 images ship pip without setuptools; preinstall the build
# backend since --no-build-isolation skips build requirements.
# [tpu] extra: the demo pods run JAX workloads from this same image, and
# jax[tpu] ships the TPU PJRT plugin + libtpu so they actually see the
# chips (plain jax would silently fall back to CPU on a TPU-VM node).
RUN pip install --no-cache-dir setuptools wheel \
    && make -C gpushare_device_plugin_tpu/native \
    && pip install --no-cache-dir --prefix=/install --no-build-isolation ".[tpu]"

FROM --platform=linux/amd64 python:3.12-slim
# grpcio + protobuf come from the wheel install in the builder stage.
COPY --from=builder /install /usr/local
COPY --from=builder /src/gpushare_device_plugin_tpu/native/libtpuinfo.so \
    /usr/local/lib/python3.12/site-packages/gpushare_device_plugin_tpu/native/libtpuinfo.so
ENV PYTHONUNBUFFERED=1
# The daemon; the same image serves the extender and the inspect CLI
# (command: overrides in the manifests).
ENTRYPOINT ["tpushare-device-plugin"]
